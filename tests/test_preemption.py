"""Priority preemption as a dense kernel pass (ops/preempt.py +
scheduler/tpu.py + the Plan.node_preemptions leg): kernel-level victim
selection invariants, the plan applier's per-victim verification, the
CPU-oracle differential judgment, the red-pressure priority-storm soak
with preemption ON vs OFF, victim-lost chaos, and jit-cache stability
with the preemption leg compiled in."""

import random
import time

import numpy as np
import pytest

from nomad_tpu import mock
from nomad_tpu.chaos import FaultSpec, chaos
from nomad_tpu.migrate import (
    configure,
    preempt_stats,
    select_victims_host,
    victim_priority,
)
from nomad_tpu.ops.binpack import (
    PlacementConfig,
    host_prng_key,
    make_asks,
    make_node_state,
)
from nomad_tpu.ops.preempt import (
    PREEMPT_MAX_VICTIMS,
    make_victim_state,
    preempt_placement_program_jit,
)
from nomad_tpu.scheduler.testing import Harness
from nomad_tpu.server import Server, ServerConfig
from nomad_tpu.structs import consts
from nomad_tpu.structs.eval import new_eval

V = PREEMPT_MAX_VICTIMS


@pytest.fixture(autouse=True)
def _restore_globals():
    yield
    chaos.disarm()
    configure(migrate_max_parallel=32, preemption_enabled=False,
              preempt_priority_threshold=50)
    # Drop the test probe so a later default-configured Server rewires
    # its own.
    from nomad_tpu.migrate import _policy

    _policy.configure(pressure_probe=lambda: "green")


def wait_until(fn, timeout=60.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if fn():
            return True
        time.sleep(interval)
    return False


# ---------------------------------------------------------------------
# kernel units


def _kernel_state(n=4, util=90.0, cap=100.0):
    capacity = np.full((n, 4), cap, np.float32)
    return make_node_state(
        capacity=capacity, sched_capacity=capacity,
        util=np.full((n, 4), util, np.float32),
        bw_avail=np.full(n, 1000.0), bw_used=np.zeros(n),
        ports_free=np.full(n, 20.0),
        job_count=np.zeros(n), tg_count=np.zeros((n, 1)),
        feasible=np.ones((n, 1), bool), node_ok=np.ones(n, bool),
    )


def _kernel_asks(k, res):
    return make_asks(
        resources=np.full((k, 4), res, np.float32), bw=np.zeros(k),
        ports=np.zeros(k), tg_index=np.zeros(k, np.int32),
        active=np.ones(k, bool), job_distinct_hosts=False,
        tg_distinct_hosts=np.zeros(1, bool))


def _victims(n, entries):
    """entries: {node_row: [(res, prio), ...]} priority-ascending."""
    res = np.zeros((n, V, 4), np.float32)
    bw = np.zeros((n, V), np.float32)
    ports = np.zeros((n, V), np.float32)
    prio = np.full((n, V), np.inf, np.float32)
    ok = np.zeros((n, V), bool)
    for row, lst in entries.items():
        for v, (r, p) in enumerate(lst):
            res[row, v] = r
            prio[row, v] = p
            ok[row, v] = True
    return make_victim_state(res, bw, ports, prio, ok)


CFG = PlacementConfig(anti_affinity_penalty=10.0)


def test_kernel_selects_lowest_priority_prefix():
    state = _kernel_state()
    victims = _victims(4, {0: [(30.0, 10), (30.0, 20)]})
    asks = _kernel_asks(2, 25.0)
    choices, _s, counts = preempt_placement_program_jit(
        state, victims, asks, host_prng_key(7), np.float32(50.0), CFG)
    # Both asks land on node 0, each consuming ONE victim in sorted
    # order; the scan carries consumption so the second ask needs the
    # second victim.
    assert list(np.asarray(choices)) == [0, 0]
    assert list(np.asarray(counts)) == [1, 1]


def test_kernel_prefers_normal_fit_over_preemption():
    state = _kernel_state(util=90.0)
    # node 2 has headroom without eviction
    state.util[2, :] = 10.0
    victims = _victims(4, {0: [(60.0, 10)], 1: [(60.0, 10)]})
    asks = _kernel_asks(1, 25.0)
    choices, _s, counts = preempt_placement_program_jit(
        state, victims, asks, host_prng_key(3), np.float32(50.0), CFG)
    assert int(np.asarray(choices)[0]) == 2
    assert int(np.asarray(counts)[0]) == 0  # no eviction needed


def test_kernel_never_evicts_equal_or_higher_priority():
    state = _kernel_state()
    victims = _victims(4, {0: [(60.0, 50)], 1: [(60.0, 80)]})
    asks = _kernel_asks(1, 25.0)
    choices, _s, counts = preempt_placement_program_jit(
        state, victims, asks, host_prng_key(5), np.float32(50.0), CFG)
    # eval priority 50: neither the prio-50 nor the prio-80 victim is
    # outrankable -> no placement at all
    assert int(np.asarray(choices)[0]) == -1
    assert int(np.asarray(counts)[0]) == 0


def test_kernel_prefix_stops_at_first_fit():
    state = _kernel_state(util=95.0)
    # evicting the first (prio 5, 40 units) suffices for a 25 ask;
    # the prio-30 second victim must survive
    victims = _victims(4, {1: [(40.0, 5), (40.0, 30)]})
    asks = _kernel_asks(1, 25.0)
    choices, _s, counts = preempt_placement_program_jit(
        state, victims, asks, host_prng_key(9), np.float32(50.0), CFG)
    assert int(np.asarray(choices)[0]) == 1
    assert int(np.asarray(counts)[0]) == 1


# ---------------------------------------------------------------------
# host oracle


def _stub_alloc(prio, cpu, create_index=0):
    a = mock.alloc()
    job = mock.job()
    job.priority = prio
    a.job = job
    a.job_id = job.id
    a.create_index = create_index
    a.task_resources = {
        "web": __import__(
            "nomad_tpu.structs", fromlist=["Resources"]).Resources(
                cpu=cpu, memory_mb=10)}
    a.shared_resources = None
    return a


def test_select_victims_host_lowest_first_minimal_prefix():
    allocs = [_stub_alloc(30, 100, 2), _stub_alloc(10, 100, 1),
              _stub_alloc(20, 100, 3)]
    victims = select_victims_host(allocs, (150.0, 0, 0, 0), 50)
    assert [victim_priority(a) for a in victims] == [10, 20]
    assert select_victims_host(allocs, (1000.0, 0, 0, 0), 50) is None
    # priority gate: nothing outrankable
    assert select_victims_host(allocs, (50.0, 0, 0, 0), 10) is None


# ---------------------------------------------------------------------
# plan-applier verification of the preemption leg


def _applier_fixture():
    server = Server(ServerConfig(num_schedulers=0))
    server.start()
    node = mock.node()
    node.resources.cpu = 1000
    node.compute_class()
    server.node_register(node)
    low = mock.job()
    low.priority = 20
    low.task_groups[0].count = 1
    low.task_groups[0].tasks[0].resources.cpu = 600
    low.task_groups[0].tasks[0].resources.networks = []
    server.log.apply("job_register", {"job": low})
    victim = mock.alloc()
    victim.job = server.fsm.state.job_by_id(low.id)
    victim.job_id = low.id
    victim.node_id = node.id
    victim.task_group = low.task_groups[0].name
    victim.task_resources = {
        "web": low.task_groups[0].tasks[0].resources.copy()}
    server.log.apply("alloc_update", {"allocs": [victim],
                                      "job": victim.job})
    return server, node, victim


def _preempt_plan(server, node, victim, priority=60):
    from nomad_tpu.scheduler.util import ALLOC_PREEMPTED
    from nomad_tpu.structs import Plan
    from nomad_tpu.utils.ids import generate_uuid

    high = mock.job()
    high.priority = priority
    high.task_groups[0].tasks[0].resources.cpu = 700
    high.task_groups[0].tasks[0].resources.networks = []
    plan = Plan(eval_id=generate_uuid(), priority=priority, job=high)
    plan.append_preemption(victim, consts.ALLOC_DESIRED_EVICT,
                           ALLOC_PREEMPTED)
    new = mock.alloc()
    new.job = high
    new.job_id = high.id
    new.node_id = node.id
    new.task_group = high.task_groups[0].name
    new.task_resources = {
        "web": high.task_groups[0].tasks[0].resources.copy()}
    plan.append_alloc(new)
    return plan, new


def _submit(server, plan):
    # Straight into the plan queue: these tests target the applier's
    # verification/commit, not the broker's eval-token guard.
    return server.plan_queue.enqueue(plan).wait(timeout=10.0)


def test_applier_commits_verified_preemption_exactly_once():
    server, node, victim = _applier_fixture()
    try:
        before = preempt_stats()["evictions_committed"]
        plan, new = _preempt_plan(server, node, victim)
        result = _submit(server, plan)
        assert result.node_preemptions, result
        state = server.fsm.state
        stored = state.alloc_by_id(victim.id)
        assert stored.desired_status == consts.ALLOC_DESIRED_EVICT
        # the victim keeps ITS OWN job on the stored record, not the
        # preemptor's (the funnel's denormalization repair)
        assert stored.job is not None and stored.job.id == victim.job_id
        assert state.alloc_by_id(new.id) is not None
        assert preempt_stats()["evictions_committed"] == before + 1
    finally:
        server.shutdown()


def test_applier_rejects_lost_victim_and_commits_nothing():
    server, node, victim = _applier_fixture()
    try:
        # the victim completes before the plan verifies: its freed
        # capacity is void and the 700-cpu placement cannot fit
        done = victim.copy()
        done.client_status = consts.ALLOC_CLIENT_COMPLETE
        server.log.apply("alloc_client_update", {"allocs": [done]})
        plan, new = _preempt_plan(server, node, victim)
        result = _submit(server, plan)
        assert result.is_no_op(), result
        assert result.refresh_index > 0
        assert server.fsm.state.alloc_by_id(new.id) is None
    finally:
        server.shutdown()


def test_applier_rejects_outranked_preemption():
    server, node, victim = _applier_fixture()
    try:
        # plan priority 20 does NOT outrank the prio-20 victim
        plan, new = _preempt_plan(server, node, victim, priority=20)
        result = _submit(server, plan)
        assert result.is_no_op(), result
        stored = server.fsm.state.alloc_by_id(victim.id)
        assert stored.desired_status != consts.ALLOC_DESIRED_EVICT
    finally:
        server.shutdown()


# ---------------------------------------------------------------------
# scheduler end-to-end (harness): the priority storm, ON vs OFF


def _storm_harness(seed, n_nodes=4):
    h = Harness(seed=seed)
    nodes = []
    for _ in range(n_nodes):
        n = mock.node()
        n.resources.cpu = 1000
        n.resources.memory_mb = 4096
        n.compute_class()
        h.state.upsert_node(h.next_index(), n)
        nodes.append(n)
    low = mock.job()
    low.id = "low-prio"
    low.priority = 20
    low.task_groups[0].count = n_nodes
    t = low.task_groups[0].tasks[0]
    t.resources.cpu = 600
    t.resources.memory_mb = 256
    t.resources.networks = []
    h.state.upsert_job(h.next_index(), low)
    h.process("service-tpu", new_eval(h.state.job_by_id(low.id),
                                      consts.EVAL_TRIGGER_JOB_REGISTER))
    live = [a for a in h.state.allocs_by_job(low.id)
            if not a.terminal_status()]
    assert len(live) == n_nodes  # one per node: the cluster is full
    high = mock.job()
    high.id = "high-prio"
    high.priority = 60
    high.task_groups[0].count = n_nodes
    t = high.task_groups[0].tasks[0]
    t.resources.cpu = 500
    t.resources.memory_mb = 128
    t.resources.networks = []
    h.state.upsert_job(h.next_index(), high)
    return h, low, high


def test_priority_storm_preemption_on_places_all():
    configure(preemption_enabled=True, preempt_priority_threshold=50,
              pressure_probe=lambda: "red")
    h, low, high = _storm_harness(seed=31)
    h.process("service-tpu", new_eval(h.state.job_by_id(high.id),
                                      consts.EVAL_TRIGGER_JOB_REGISTER))
    state = h.state
    high_live = [a for a in state.allocs_by_job(high.id)
                 if not a.terminal_status()]
    assert len(high_live) == 4, h.evals[-1].failed_tg_allocs
    evicted = [a for a in state.allocs_by_job(low.id)
               if a.desired_status == consts.ALLOC_DESIRED_EVICT]
    assert len(evicted) == 4
    # lowest-priority-first per node: no surviving alloc on a victim
    # node outranks downward an evicted one (all victims were the
    # lowest-priority allocs on their nodes)
    for a in evicted:
        survivors = [s for s in state.allocs_by_node(a.node_id)
                     if not s.terminal_status() and s.job_id != high.id]
        assert all(victim_priority(s) >= victim_priority(a)
                   for s in survivors)
    # the victim job got its replacement eval through the funnel
    follow = [e for e in h.create_evals
              if e.triggered_by == consts.EVAL_TRIGGER_PREEMPTION]
    assert [e.job_id for e in follow] == [low.id]
    # eval completed
    assert h.evals[-1].status == consts.EVAL_STATUS_COMPLETE


def test_priority_storm_preemption_off_sheds_unchanged():
    configure(preemption_enabled=False, pressure_probe=lambda: "red")
    h, low, high = _storm_harness(seed=32)
    h.process("service-tpu", new_eval(h.state.job_by_id(high.id),
                                      consts.EVAL_TRIGGER_JOB_REGISTER))
    state = h.state
    assert [a for a in state.allocs_by_job(high.id)
            if not a.terminal_status()] == []
    assert [a for a in state.allocs_by_job(low.id)
            if a.desired_status == consts.ALLOC_DESIRED_EVICT] == []
    # the PR 5 outcome: a blocked eval waits for capacity
    assert any(e.status == consts.EVAL_STATUS_BLOCKED
               for e in h.create_evals)


def test_priority_storm_green_cluster_never_preempts():
    configure(preemption_enabled=True, preempt_priority_threshold=50,
              pressure_probe=lambda: "green")
    h, low, high = _storm_harness(seed=33)
    h.process("service-tpu", new_eval(h.state.job_by_id(high.id),
                                      consts.EVAL_TRIGGER_JOB_REGISTER))
    assert [a for a in h.state.allocs_by_job(low.id)
            if a.desired_status == consts.ALLOC_DESIRED_EVICT] == []


def test_preemption_leg_jit_cache_is_stable():
    """Steady-state jit_recompiles stays 0 with the preemption leg
    compiled in: a second storm of identical shape adds no programs."""
    from nomad_tpu.ops.binpack import jit_cache_size

    configure(preemption_enabled=True, preempt_priority_threshold=50,
              pressure_probe=lambda: "red")
    h, low, high = _storm_harness(seed=34)
    h.process("service-tpu", new_eval(h.state.job_by_id(high.id),
                                      consts.EVAL_TRIGGER_JOB_REGISTER))
    warm = jit_cache_size()
    h2, low2, high2 = _storm_harness(seed=35)
    h2.process("service-tpu", new_eval(h2.state.job_by_id(high2.id),
                                       consts.EVAL_TRIGGER_JOB_REGISTER))
    assert jit_cache_size() == warm


# ---------------------------------------------------------------------
# oracle differential: randomized clusters judge the kernel's choices


@pytest.mark.parametrize("seed", range(700, 708))
def test_preemption_differential_validity(seed):
    """Whatever the kernel chose, the committed state must satisfy the
    CPU oracle's invariants: victims strictly outranked, lowest-
    priority-first per node, and every node's post-commit load fits
    its capacity exactly (allocs_fit)."""
    from nomad_tpu.structs import allocs_fit

    rng = random.Random(seed)
    configure(preemption_enabled=True, preempt_priority_threshold=50,
              pressure_probe=lambda: "red")
    h = Harness(seed=seed)
    n_nodes = rng.choice([4, 6])
    nodes = []
    for _ in range(n_nodes):
        n = mock.node()
        n.resources.cpu = 1000
        n.resources.memory_mb = 4096
        n.compute_class()
        h.state.upsert_node(h.next_index(), n)
        nodes.append(n)
    # random low-priority fill
    for j in range(rng.choice([2, 3])):
        job = mock.job()
        job.id = f"low-{j}"
        job.priority = rng.choice([10, 20, 30])
        job.task_groups[0].count = n_nodes
        t = job.task_groups[0].tasks[0]
        t.resources.cpu = rng.choice([300, 400])
        t.resources.memory_mb = 128
        t.resources.networks = []
        h.state.upsert_job(h.next_index(), job)
        h.process("service-tpu", new_eval(
            h.state.job_by_id(job.id), consts.EVAL_TRIGGER_JOB_REGISTER))
    high = mock.job()
    high.id = "high"
    high.priority = rng.choice([60, 80])
    high.task_groups[0].count = rng.choice([4, 5])
    t = high.task_groups[0].tasks[0]
    t.resources.cpu = rng.choice([400, 500])
    t.resources.memory_mb = 128
    t.resources.networks = []
    h.state.upsert_job(h.next_index(), high)
    h.process("service-tpu", new_eval(
        h.state.job_by_id(high.id), consts.EVAL_TRIGGER_JOB_REGISTER))

    state = h.state
    evicted = [a for a in state.allocs()
               if a.desired_status == consts.ALLOC_DESIRED_EVICT]
    for a in evicted:
        assert victim_priority(a) < high.priority, seed
        survivors = [s for s in state.allocs_by_node(a.node_id)
                     if not s.terminal_status() and s.job_id != high.id]
        assert all(victim_priority(s) >= victim_priority(a)
                   for s in survivors), seed
    # post-commit exact fit on every node the oracle can check
    for n in nodes:
        live = [a for a in state.allocs_by_node(n.id)
                if not a.terminal_status()]
        fit, _dim, _util = allocs_fit(n, live)
        assert fit, (seed, n.id)


# ---------------------------------------------------------------------
# live-server soak: victim lost mid-commit, exactly-once through raft


def test_server_preemption_soak_with_victim_lost_chaos():
    server = Server(ServerConfig(
        num_schedulers=2,
        scheduler_factories={"service": "service-tpu"},
        dense_min_batch=1,
        eval_nack_timeout=2.0,
        eval_delivery_limit=8,
        preemption_enabled=True,
        preempt_priority_threshold=50,
    ))
    server.start()
    try:
        nodes = []
        for _ in range(4):
            node = mock.node()
            node.resources.cpu = 1000
            node.compute_class()
            server.node_register(node)
            nodes.append(node)
        low = mock.job()
        low.id = "low-prio"
        low.priority = 20
        low.task_groups[0].count = 4
        t = low.task_groups[0].tasks[0]
        t.resources.cpu = 600
        t.resources.memory_mb = 256
        t.resources.networks = []
        server.job_register(low)

        def live(job_id):
            return [a for a in server.fsm.state.allocs_by_job(job_id)
                    if not a.terminal_status()]

        assert wait_until(lambda: len(live(low.id)) == 4, 60.0)

        # red pressure + a victim lost between selection and commit
        server.admission.force_level("red")
        chaos.arm(99, [FaultSpec("preempt.victim_lost", "drop", count=1)])
        high = mock.job()
        high.id = "high-prio"
        high.priority = 60
        high.task_groups[0].count = 4
        t = high.task_groups[0].tasks[0]
        t.resources.cpu = 500
        t.resources.memory_mb = 128
        t.resources.networks = []
        server.job_register(high)

        assert wait_until(lambda: len(live(high.id)) == 4, 60.0), (
            server.fsm.state.evals_by_job(high.id))
        fired = chaos.firing_log()
        chaos.disarm()
        assert [f for f in fired if f[0] == "preempt.victim_lost"]

        state = server.fsm.state
        evicted = [a for a in state.allocs_by_job(low.id)
                   if a.desired_status == consts.ALLOC_DESIRED_EVICT]
        assert len(evicted) == 4
        # exactly once: one store record per victim id, stamped evict
        assert len({a.id for a in evicted}) == 4
        # nothing placed on top of a surviving victim: per-node fit
        from nomad_tpu.structs import allocs_fit

        for node in nodes:
            livehere = [a for a in state.allocs_by_node(node.id)
                        if not a.terminal_status()]
            fit, _d, _u = allocs_fit(node, livehere)
            assert fit, node.id
        # the high-prio evals all completed; the victims' replacement
        # evals exist (blocked or pending — the cluster is full, which
        # is the correct PR 5 outcome for prio-20 work on a red box)
        for e in state.evals_by_job(high.id):
            assert e.terminal_status(), e
        assert [e for e in state.evals_by_job(low.id)
                if e.triggered_by == consts.EVAL_TRIGGER_PREEMPTION]
    finally:
        chaos.disarm()
        server.admission.force_level(None)
        server.shutdown()
