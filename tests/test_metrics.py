"""Telemetry: inmem interval sink, statsd UDP sink, and the gauges/
samples emitted by the control plane (reference go-metrics fanout,
command/agent/command.go:570)."""

import socket
import time

from nomad_tpu import mock
from nomad_tpu.utils.metrics import InmemSink, Metrics, StatsdSink


def test_inmem_counter_gauge_sample_aggregation():
    sink = InmemSink(interval=60.0)
    sink.incr_counter("a.b", 1)
    sink.incr_counter("a.b", 3)
    sink.set_gauge("g", 7.0)
    sink.set_gauge("g", 9.0)  # last write wins within the interval
    for v in (5.0, 1.0, 3.0):
        sink.add_sample("s", v)

    snap = sink.snapshot()[-1]
    assert snap["counters"]["a.b"] == {"count": 2, "sum": 4}
    assert snap["gauges"]["g"] == 9.0
    s = snap["samples"]["s"]
    assert s["count"] == 3 and s["min"] == 1.0 and s["max"] == 5.0
    assert abs(s["mean"] - 3.0) < 1e-9


def test_inmem_interval_rotation():
    sink = InmemSink(interval=0.01, retain=3)
    for i in range(6):
        sink.incr_counter("c", 1)
        time.sleep(0.015)
    assert len(sink._intervals) <= 3


def test_metrics_prefix_and_measure_since():
    m = Metrics(prefix="test")
    start = time.monotonic()
    time.sleep(0.01)
    m.measure_since(("stage", "x"), start)
    snap = m.snapshot()[-1]
    (name,) = snap["samples"].keys()
    assert name == "test.stage.x"
    assert snap["samples"][name]["max"] >= 10.0  # milliseconds


def test_statsd_sink_sends_datagrams():
    recv = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    recv.bind(("127.0.0.1", 0))
    recv.settimeout(2.0)
    port = recv.getsockname()[1]

    m = Metrics(prefix="nomad_tpu")
    m.add_sink(StatsdSink(f"127.0.0.1:{port}"))
    m.incr_counter(("rpc", "query"), 1)
    m.set_gauge(("broker", "depth"), 5)
    m.add_sample(("plan", "evaluate"), 12.5)

    got = set()
    for _ in range(3):
        got.add(recv.recv(1024).decode())
    assert "nomad_tpu.rpc.query:1|c" in got
    assert "nomad_tpu.broker.depth:5|g" in got
    assert "nomad_tpu.plan.evaluate:12.5|ms" in got
    recv.close()


def test_server_emits_worker_and_fsm_samples():
    """End to end: registering and scheduling a job must produce fsm/
    worker/plan timing samples in the global registry."""
    from nomad_tpu.server import Server, ServerConfig
    from nomad_tpu.utils import metrics as gm

    gm.configure()  # fresh global registry
    s = Server(ServerConfig(num_schedulers=1, telemetry_interval=0.05))
    s.start()
    try:
        for i in range(3):
            s.fsm.state.upsert_node(i + 1, mock.node())
        job = mock.job()
        s.job_register(job)

        deadline = time.monotonic() + 5.0
        needed = {
            "nomad_tpu.fsm.job_register",
            "nomad_tpu.worker.invoke_scheduler.service",
            "nomad_tpu.plan.evaluate",
        }
        while time.monotonic() < deadline:
            seen = set()
            for iv in gm.get_metrics().snapshot():
                seen |= set(iv["samples"])
            if needed <= seen:
                break
            time.sleep(0.05)
        assert needed <= seen, f"missing: {needed - seen}"

        # gauge loop fires on telemetry_interval
        deadline = time.monotonic() + 3.0
        while time.monotonic() < deadline:
            gauges = {}
            for iv in gm.get_metrics().snapshot():
                gauges.update(iv["gauges"])
            if "nomad_tpu.broker.total_ready" in gauges:
                break
            time.sleep(0.05)
        assert "nomad_tpu.broker.total_ready" in gauges
    finally:
        s.shutdown()
        gm.configure()  # reset global for other tests
