"""Telemetry: inmem interval sink, statsd UDP sink, and the gauges/
samples emitted by the control plane (reference go-metrics fanout,
command/agent/command.go:570)."""

import socket
import time

from nomad_tpu import mock
from nomad_tpu.utils.metrics import InmemSink, Metrics, StatsdSink


def test_inmem_counter_gauge_sample_aggregation():
    sink = InmemSink(interval=60.0)
    sink.incr_counter("a.b", 1)
    sink.incr_counter("a.b", 3)
    sink.set_gauge("g", 7.0)
    sink.set_gauge("g", 9.0)  # last write wins within the interval
    for v in (5.0, 1.0, 3.0):
        sink.add_sample("s", v)

    snap = sink.snapshot()[-1]
    assert snap["counters"]["a.b"] == {"count": 2, "sum": 4}
    assert snap["gauges"]["g"] == 9.0
    s = snap["samples"]["s"]
    assert s["count"] == 3 and s["min"] == 1.0 and s["max"] == 5.0
    assert abs(s["mean"] - 3.0) < 1e-9


def test_hist_bucket_math_at_extremes():
    """Log-bucket ladder edges: zero/negative land in the dedicated
    zero bucket, sub-ms values in the floor bucket, multi-second values
    in a finite bucket whose bound brackets them within one ratio step,
    and absurd values clamp to the last bucket instead of overflowing."""
    from nomad_tpu.utils.metrics import (
        HIST_BUCKETS,
        HIST_MIN_MS,
        HIST_RATIO,
        hist_bucket,
        hist_bucket_upper,
        hist_percentile,
    )

    assert hist_bucket(0.0) == 0 and hist_bucket(-3.0) == 0
    assert hist_bucket_upper(0) == 0.0
    assert hist_bucket(1e-7) == 1 and hist_bucket(HIST_MIN_MS) == 1
    for v in (0.004, 0.7, 12.5, 5_000.0, 3_600_000.0):  # sub-ms .. 1h
        b = hist_bucket(v)
        assert 1 < b < HIST_BUCKETS - 1
        assert v <= hist_bucket_upper(b) <= v * HIST_RATIO * (1 + 1e-9)
    assert hist_bucket(1e15) == HIST_BUCKETS - 1  # clamp, no IndexError
    # percentiles: empty -> 0; all-zero samples -> 0 (the zero bucket)
    assert hist_percentile([0] * HIST_BUCKETS, 0, 0.99) == 0.0
    zeros = [0] * HIST_BUCKETS
    zeros[0] = 10
    assert hist_percentile(zeros, 10, 0.99) == 0.0


def test_inmem_sample_percentiles():
    """p50/p95/p99 recoverable from any interval snapshot (the old
    count/sum/min/max could not reconstruct a percentile) — within one
    bucket-ratio step of the true order statistic."""
    import numpy as np

    from nomad_tpu.utils.metrics import HIST_RATIO

    sink = InmemSink(interval=60.0)
    vals = [0.0, 0.0004] + [float(i) for i in range(1, 999)] + [7200.0]
    for v in vals:
        sink.add_sample("mixed", v)
    s = sink.snapshot()[-1]["samples"]["mixed"]
    for q, key in ((0.50, "p50"), (0.95, "p95"), (0.99, "p99")):
        true = float(np.percentile(vals, q * 100))
        assert true <= s[key] <= max(true, 1e-3) * HIST_RATIO * 1.02, (
            q, true, s[key])
    assert s["count"] == len(vals) and s["min"] == 0.0


def test_statsd_wire_format_unchanged_by_histograms():
    """The statsd/statsite sinks' line protocol must not grow bucket
    baggage — only the inmem sink aggregates histograms."""
    recv = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    recv.bind(("127.0.0.1", 0))
    recv.settimeout(2.0)
    port = recv.getsockname()[1]
    m = Metrics(prefix="nomad_tpu")
    m.add_sink(StatsdSink(f"127.0.0.1:{port}"))
    m.add_sample(("plan", "evaluate"), 12.5)
    assert recv.recv(1024).decode() == "nomad_tpu.plan.evaluate:12.5|ms"
    recv.close()


def test_prometheus_counters_survive_interval_rotation():
    """The exposition reads LIFETIME aggregates: counters must not
    shrink when old intervals rotate out of the inmem ring (a shrinking
    _total reads as a counter reset to rate())."""
    from nomad_tpu.utils.metrics import Metrics, format_prometheus

    m = Metrics(prefix="nt")
    m.inmem.interval = 0.01
    m.inmem.retain = 2
    for _ in range(5):
        m.incr_counter(("c",), 1)
        m.add_sample(("s",), 1.0)
        time.sleep(0.015)
    # rolling window kept only 2 intervals...
    assert len(m.inmem._intervals) <= 2
    text = format_prometheus(m)
    # ...but the exposed totals cover all 5 increments
    assert "nt_c_total 5" in text
    assert "nt_s_count 5" in text


def test_prometheus_exposition_shape():
    from nomad_tpu.utils.metrics import Metrics, format_prometheus

    m = Metrics(prefix="nt")
    m.incr_counter(("rpc", "query"), 3)
    m.set_gauge(("broker", "depth"), 5)
    for v in (1.0, 2.0, 400.0):
        m.add_sample(("plan", "evaluate"), v)
    text = format_prometheus(m)
    assert "# TYPE nt_rpc_query_total counter" in text
    assert "nt_rpc_query_total 3" in text
    assert "# TYPE nt_broker_depth gauge" in text
    assert "# TYPE nt_plan_evaluate histogram" in text
    assert 'nt_plan_evaluate_bucket{le="+Inf"} 3' in text
    assert "nt_plan_evaluate_count 3" in text
    assert "nt_plan_evaluate_sum 403" in text
    # cumulative: bucket counts never decrease
    cums = [int(line.rsplit(" ", 1)[1]) for line in text.splitlines()
            if line.startswith("nt_plan_evaluate_bucket")]
    assert cums == sorted(cums)


def _parse_exposition(text):
    """Line-level Prometheus 0.0.4 text-format parser (the conformance
    gate for /v1/metrics): validates comment structure, metric-name and
    label syntax, value syntax (including +Inf/-Inf/NaN spellings),
    single TYPE per family declared before its samples, and — for
    histograms — per-labelset le-ascending CUMULATIVE buckets ending in
    +Inf whose count equals _count, with _sum/_count present. Returns
    {family: type}; raises AssertionError on any violation."""
    import re

    name_re = r"[a-zA-Z_:][a-zA-Z0-9_:]*"
    label_re = re.compile(
        r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')
    sample_re = re.compile(
        rf"^({name_re})(?:\{{(.*)\}})? (\S+)$")
    types = {}
    seen_sample_families = set()
    # (family, frozenset(non-le labels)) -> [(le, cum)] + flags
    hist_series = {}
    hist_sum = set()
    hist_count = {}

    def family_of(name):
        for suffix in ("_bucket", "_sum", "_count", "_total"):
            if name.endswith(suffix) and name[: -len(suffix)] in types:
                return name[: -len(suffix)], suffix
        return name, ""

    def parse_value(v):
        if v in ("+Inf", "-Inf", "NaN"):
            return float(v.replace("Inf", "inf").replace("NaN", "nan"))
        return float(v)  # raises on malformed

    lines = text.splitlines()
    assert text.endswith("\n"), "exposition must end with a newline"
    for line in lines:
        assert line == line.strip(), f"stray whitespace: {line!r}"
        if not line:
            continue
        if line.startswith("#"):
            m = re.match(rf"^# (HELP|TYPE) ({name_re})(?: (.*))?$", line)
            assert m, f"malformed comment line: {line!r}"
            if m.group(1) == "TYPE":
                fam = m.group(2)
                for suffix in ("_total",):
                    if fam.endswith(suffix):
                        fam = fam  # _total families are declared whole
                assert fam not in types, f"duplicate TYPE for {fam}"
                assert fam not in seen_sample_families, (
                    f"TYPE for {fam} after its samples")
                assert m.group(3) in (
                    "counter", "gauge", "histogram", "summary",
                    "untyped"), f"bad type: {line!r}"
                types[fam] = m.group(3)
            continue
        m = sample_re.match(line)
        assert m, f"malformed sample line: {line!r}"
        name, labels_raw, value_raw = m.groups()
        value = parse_value(value_raw)
        labels = {}
        if labels_raw:
            consumed = label_re.sub("", labels_raw).strip(", ")
            assert consumed == "", f"malformed labels: {line!r}"
            labels = dict(label_re.findall(labels_raw))
        fam, suffix = family_of(name)
        assert fam in types, f"sample before any TYPE: {line!r}"
        seen_sample_families.add(fam)
        if types[fam] == "histogram":
            key = (fam, frozenset(
                (k, v) for k, v in labels.items() if k != "le"))
            if suffix == "_bucket":
                le = labels.get("le")
                assert le is not None, f"bucket without le: {line!r}"
                le_v = parse_value(le)
                series = hist_series.setdefault(key, [])
                if series:
                    assert le_v > series[-1][0], (
                        f"le not ascending: {line!r}")
                    assert value >= series[-1][1], (
                        f"cumulative count decreased: {line!r}")
                series.append((le_v, value))
            elif suffix == "_sum":
                hist_sum.add(key)
            elif suffix == "_count":
                hist_count[key] = value
    for key, series in hist_series.items():
        fam = key[0]
        assert series, f"histogram {fam} with no buckets"
        assert series[-1][0] == float("inf"), (
            f"histogram {fam} missing +Inf bucket")
        assert key in hist_sum, f"histogram {fam} missing _sum"
        assert key in hist_count, f"histogram {fam} missing _count"
        assert series[-1][1] == hist_count[key], (
            f"histogram {fam}: +Inf bucket != _count")
    return types


def test_prometheus_exposition_line_level_conformance():
    """The 0.0.4 parser gate over a fully-populated registry: every
    line must parse, histograms must be cumulative/le-ordered with
    +Inf/_sum/_count, TYPE once per family before its samples."""
    from nomad_tpu.utils.metrics import Metrics, format_prometheus

    m = Metrics(prefix="nt")
    m.incr_counter(("rpc", "query"), 3)
    m.incr_counter(("broker", "shed"), 1)
    m.set_gauge(("broker", "depth"), 5.5)
    m.set_gauge(("weird", "gauge"), float("nan"))  # must not crash
    m.set_gauge(("inf", "gauge"), float("inf"))
    for v in (0.0, 0.5, 1.0, 2.0, 400.0, 9e9):
        m.add_sample(("plan", "evaluate"), v)
    for v in (1.0, 3.0):
        m.add_sample(("http", "request", "GET", "jobs"), v)
    text = format_prometheus(m)
    types = _parse_exposition(text)
    assert types["nt_rpc_query_total"] == "counter"
    assert types["nt_broker_depth"] == "gauge"
    assert types["nt_plan_evaluate"] == "histogram"
    assert "NaN" in text and "+Inf" in text  # exposition spellings


def test_prometheus_exposition_name_collision_single_family():
    """Two raw names sanitizing to one prom name must not emit two
    TYPE blocks (a parse error for every scraper): first wins."""
    from nomad_tpu.utils.metrics import Metrics, format_prometheus

    m = Metrics(prefix="nt")
    m.add_sample(("a.b", "x"), 1.0)
    m.add_sample(("a_b", "x"), 2.0)
    text = format_prometheus(m)
    assert text.count("# TYPE nt_a_b_x histogram") == 1
    _parse_exposition(text)


def test_profile_exposition_passes_conformance_parser():
    """The observatory's labelled histograms ride the same gate: the
    combined /v1/metrics body (registry + profiler) must parse line by
    line."""
    import threading

    from nomad_tpu import profile
    from nomad_tpu.profile import ProfiledLock, get_profiler
    from nomad_tpu.utils.metrics import Metrics, format_prometheus

    prof = get_profiler()
    prof.reset()
    lock = ProfiledLock("conf.site")

    def holder():
        with lock:
            time.sleep(0.02)

    t = threading.Thread(target=holder)
    t.start()
    time.sleep(0.005)
    with lock:
        pass
    t.join()
    profile.record_runq("batch_park", 2.0)
    profile.park("conf.park")
    profile.unpark("conf.park")
    m = Metrics(prefix="nt")
    m.incr_counter(("rpc", "query"), 1)
    text = format_prometheus(m) + prof.format_prometheus()
    types = _parse_exposition(text)
    assert types["nomad_tpu_profile_lock_wait_ms"] == "histogram"
    assert types["nomad_tpu_profile_runq_delay_ms"] == "histogram"
    assert types["nomad_tpu_profile_convoys_total"] == "counter"
    prof.reset()


def test_inmem_interval_rotation():
    sink = InmemSink(interval=0.01, retain=3)
    for i in range(6):
        sink.incr_counter("c", 1)
        time.sleep(0.015)
    assert len(sink._intervals) <= 3


def test_metrics_prefix_and_measure_since():
    m = Metrics(prefix="test")
    start = time.monotonic()
    time.sleep(0.01)
    m.measure_since(("stage", "x"), start)
    snap = m.snapshot()[-1]
    (name,) = snap["samples"].keys()
    assert name == "test.stage.x"
    assert snap["samples"][name]["max"] >= 10.0  # milliseconds


def test_statsd_sink_sends_datagrams():
    recv = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    recv.bind(("127.0.0.1", 0))
    recv.settimeout(2.0)
    port = recv.getsockname()[1]

    m = Metrics(prefix="nomad_tpu")
    m.add_sink(StatsdSink(f"127.0.0.1:{port}"))
    m.incr_counter(("rpc", "query"), 1)
    m.set_gauge(("broker", "depth"), 5)
    m.add_sample(("plan", "evaluate"), 12.5)

    got = set()
    for _ in range(3):
        got.add(recv.recv(1024).decode())
    assert "nomad_tpu.rpc.query:1|c" in got
    assert "nomad_tpu.broker.depth:5|g" in got
    assert "nomad_tpu.plan.evaluate:12.5|ms" in got
    recv.close()


def test_server_emits_worker_and_fsm_samples():
    """End to end: registering and scheduling a job must produce fsm/
    worker/plan timing samples in the global registry."""
    from nomad_tpu.server import Server, ServerConfig
    from nomad_tpu.utils import metrics as gm

    gm.configure()  # fresh global registry
    s = Server(ServerConfig(num_schedulers=1, telemetry_interval=0.05))
    s.start()
    try:
        for i in range(3):
            s.fsm.state.upsert_node(i + 1, mock.node())
        job = mock.job()
        s.job_register(job)

        deadline = time.monotonic() + 5.0
        needed = {
            "nomad_tpu.fsm.job_register",
            "nomad_tpu.worker.invoke_scheduler.service",
            "nomad_tpu.plan.evaluate",
        }
        while time.monotonic() < deadline:
            seen = set()
            for iv in gm.get_metrics().snapshot():
                seen |= set(iv["samples"])
            if needed <= seen:
                break
            time.sleep(0.05)
        assert needed <= seen, f"missing: {needed - seen}"

        # gauge loop fires on telemetry_interval
        deadline = time.monotonic() + 3.0
        while time.monotonic() < deadline:
            gauges = {}
            for iv in gm.get_metrics().snapshot():
                gauges.update(iv["gauges"])
            if "nomad_tpu.broker.total_ready" in gauges:
                break
            time.sleep(0.05)
        assert "nomad_tpu.broker.total_ready" in gauges
    finally:
        s.shutdown()
        gm.configure()  # reset global for other tests


def test_statsite_sink_tcp():
    """Statsite speaks statsd lines over persistent TCP
    (go-metrics statsite.go)."""
    import socket
    import threading

    from nomad_tpu.utils.metrics import StatsiteSink

    received = []
    ready = threading.Event()
    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    port = srv.getsockname()[1]

    def accept():
        ready.set()
        conn, _ = srv.accept()
        buf = b""
        while b"\n" not in buf or buf.count(b"\n") < 3:
            data = conn.recv(4096)
            if not data:
                break
            buf += data
        received.extend(buf.decode().strip().splitlines())
        conn.close()

    t = threading.Thread(target=accept, daemon=True)
    t.start()
    ready.wait(2.0)

    sink = StatsiteSink(f"127.0.0.1:{port}")
    sink.incr_counter("a.b", 2)
    sink.set_gauge("a.g", 7.5)
    sink.add_sample("a.s", 12.0)
    t.join(timeout=3.0)
    sink.close()
    srv.close()
    assert "a.b:2|c" in received
    assert "a.g:7.5|g" in received
    assert "a.s:12.0|ms" in received


def test_statsite_sink_survives_down_target():
    from nomad_tpu.utils.metrics import StatsiteSink

    sink = StatsiteSink("127.0.0.1:1")  # nothing listens there
    sink.incr_counter("x", 1)  # must not raise
    sink.close()


def test_hostname_tagging_gauges_only():
    """Only gauges carry the hostname (go-metrics SetGauge semantics);
    counters/samples stay cluster-aggregatable."""
    from nomad_tpu.utils.metrics import Metrics

    m = Metrics("nomad_tpu", hostname="host1")
    m.incr_counter("worker.dequeue", 1)
    m.add_sample("worker.invoke", 2.0)
    m.set_gauge("broker.ready", 3)
    counters, gauges, samples = set(), set(), set()
    for iv in m.snapshot():
        counters |= set(iv["counters"])
        gauges |= set(iv["gauges"])
        samples |= set(iv["samples"])
    assert "nomad_tpu.worker.dequeue" in counters
    assert "nomad_tpu.worker.invoke" in samples
    assert "nomad_tpu.host1.broker.ready" in gauges


def test_format_snapshot():
    from nomad_tpu.utils.metrics import Metrics, format_snapshot

    m = Metrics("t")
    m.incr_counter("c1", 3)
    m.set_gauge("g1", 9)
    m.add_sample("s1", 4.5)
    text = format_snapshot(m.snapshot())
    assert "counter t.c1: count=1 sum=3" in text
    assert "gauge t.g1: 9" in text
    assert "sample t.s1: count=1 mean=4.500" in text


def test_configure_full():
    import nomad_tpu.utils.metrics as gm

    m = gm.configure(statsd_addr="127.0.0.1:18125",
                     statsite_addr="",
                     disable_hostname=False, interval=5.0)
    try:
        assert m.hostname  # hostname tagging on
        assert m.inmem.interval == 5.0
        m.incr_counter("x", 1)  # statsd UDP send must not raise
    finally:
        gm.configure()


def test_circonus_sink_flushes_httptrap():
    """CirconusSink batches metrics and PUTs one JSON document to the
    submission URL (httptrap shape)."""
    import http.server
    import json
    import socketserver
    import threading

    from nomad_tpu.utils.metrics import CirconusSink

    received = []
    done = threading.Event()

    class Handler(http.server.BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_PUT(self):
            n = int(self.headers.get("Content-Length") or 0)
            received.append(json.loads(self.rfile.read(n)))
            self.send_response(200)
            self.send_header("Content-Length", "2")
            self.end_headers()
            self.wfile.write(b"{}")
            done.set()

    class Server(socketserver.ThreadingMixIn, http.server.HTTPServer):
        daemon_threads = True

    srv = Server(("127.0.0.1", 0), Handler)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    url = f"http://127.0.0.1:{srv.server_address[1]}/module/httptrap/x/y"
    sink = CirconusSink(url, flush_interval=3600)  # manual flush only
    try:
        sink.set_gauge("nomad_tpu.broker.total_ready", 4)
        sink.incr_counter("nomad_tpu.worker.dequeue", 1)
        sink.flush()
        assert done.wait(5.0)
        doc = received[0]
        assert doc["nomad_tpu.broker.total_ready"] == {"_type": "n",
                                                       "_value": 4}
        assert "nomad_tpu.worker.dequeue" in doc
        # a second flush with nothing pending sends nothing
        count = len(received)
        sink.flush()
        assert len(received) == count
    finally:
        sink.close()
        srv.shutdown()
        srv.server_close()


def test_circonus_sink_survives_down_endpoint():
    from nomad_tpu.utils.metrics import CirconusSink

    sink = CirconusSink("http://127.0.0.1:1/x", flush_interval=3600)
    sink.set_gauge("g", 1)
    sink.flush()  # must not raise
    sink.close()


def test_circonus_counters_accumulate():
    from nomad_tpu.utils.metrics import CirconusSink

    sink = CirconusSink("http://127.0.0.1:1/x", flush_interval=3600)
    try:
        for _ in range(5):
            sink.incr_counter("c", 1)
        sink.set_gauge("g", 1)
        sink.set_gauge("g", 9)
        with sink._lock:
            assert sink._pending["c"] == 5  # counters sum
            assert sink._pending["g"] == 9  # gauges last-write-wins
    finally:
        sink.close()
