"""Jobspec parser tests (mirror jobspec/parse_test.go)."""

import pytest

from nomad_tpu.jobspec import HCLParseError, parse, parse_hcl
from nomad_tpu.jobspec.parse import parse_duration
from nomad_tpu.structs import consts

FULL_SPEC = """
# full example spec
job "binstore-storagelocker" {
  region = "global"
  type = "service"
  priority = 52
  all_at_once = true
  datacenters = ["us2", "eu1"]

  meta {
    foo = "bar"
  }

  constraint {
    attribute = "${attr.kernel.os}"
    value = "windows"
  }

  update {
    stagger = "60s"
    max_parallel = 2
  }

  group "binsl" {
    count = 5

    restart {
      attempts = 5
      interval = "10m"
      delay = "15s"
      mode = "delay"
    }

    ephemeral_disk {
      sticky = true
      size = 150
    }

    constraint {
      attribute = "${node.class}"
      value = "fast"
    }

    task "binstore" {
      driver = "docker"
      user = "bob"

      config {
        image = "hashicorp/binstore"
      }

      env {
        HELLO = "world"
        LOREM = "ipsum"
      }

      service {
        name = "binstore"
        tags = ["foo", "bar"]
        port = "http"

        check {
          name = "check-name"
          type = "tcp"
          interval = "10s"
          timeout = "2s"
        }
      }

      resources {
        cpu = 500
        memory = 128

        network {
          mbits = 100
          port "one" { static = 1 }
          port "three" { static = 3 }
          port "http" {}
          port "https" {}
          port "admin" {}
        }
      }

      kill_timeout = "22s"

      logs {
        max_files = 10
        max_file_size = 100
      }

      artifact {
        source = "http://foo.com/artifact"
        options {
          checksum = "md5:b8a4f3f72ecab0510a6a31e997461c5f"
        }
      }
    }

    task "storagelocker" {
      driver = "java"

      config {
        jar_path = "local/x.jar"
      }

      resources {
        cpu = 500
        memory = 25
      }

      constraint {
        attribute = "${attr.kernel.arch}"
        value = "amd64"
      }
    }
  }
}
"""


def test_parse_full_spec():
    job = parse(FULL_SPEC)
    assert job.id == "binstore-storagelocker"
    assert job.region == "global"
    assert job.priority == 52
    assert job.all_at_once is True
    assert job.datacenters == ["us2", "eu1"]
    assert job.meta == {"foo": "bar"}
    assert len(job.constraints) == 1
    assert job.constraints[0].ltarget == "${attr.kernel.os}"
    assert job.update.stagger == 60.0
    assert job.update.max_parallel == 2

    assert len(job.task_groups) == 1
    tg = job.task_groups[0]
    assert tg.name == "binsl" and tg.count == 5
    assert tg.restart_policy.attempts == 5
    assert tg.restart_policy.interval == 600.0
    assert tg.restart_policy.mode == "delay"
    assert tg.ephemeral_disk.sticky and tg.ephemeral_disk.size_mb == 150

    assert len(tg.tasks) == 2
    task = tg.tasks[0]
    assert task.name == "binstore"
    assert task.driver == "docker"
    assert task.user == "bob"
    assert task.config["image"] == "hashicorp/binstore"
    assert task.env == {"HELLO": "world", "LOREM": "ipsum"}
    assert task.kill_timeout == 22.0
    assert task.log_config.max_file_size_mb == 100
    assert len(task.artifacts) == 1
    assert task.artifacts[0].getter_options["checksum"].startswith("md5:")

    res = task.resources
    assert res.cpu == 500 and res.memory_mb == 128
    net = res.networks[0]
    assert net.mbits == 100
    assert [p.label for p in net.reserved_ports] == ["one", "three"]
    assert [p.value for p in net.reserved_ports] == [1, 3]
    assert [p.label for p in net.dynamic_ports] == ["http", "https", "admin"]

    svc = task.services[0]
    assert svc.name == "binstore" and svc.port_label == "http"
    assert svc.checks[0].interval == 10.0

    task2 = tg.tasks[1]
    assert task2.name == "storagelocker"
    assert task2.constraints[0].rtarget == "amd64"


def test_parse_periodic():
    job = parse(
        'job "p" { datacenters = ["dc1"] periodic { cron = "*/5 * * * *" '
        "prohibit_overlap = true } "
        'task "t" { driver = "exec" config { command = "/bin/true" } } }'
    )
    assert job.is_periodic()
    assert job.periodic.spec == "*/5 * * * *"
    assert job.periodic.prohibit_overlap is True


def test_parse_constraint_sugar():
    job = parse(
        'job "c" { datacenters = ["dc1"] '
        'constraint { attribute = "${attr.nomad.version}" version = ">= 0.4" } '
        'constraint { distinct_hosts = true } '
        'constraint { attribute = "${attr.os}" regexp = "^lin" } '
        'task "t" { driver = "exec" config { command = "x" } } }'
    )
    ops = [c.operand for c in job.constraints]
    assert ops == [consts.CONSTRAINT_VERSION, consts.CONSTRAINT_DISTINCT_HOSTS,
                   consts.CONSTRAINT_REGEX]


def test_bare_task_gets_implicit_group():
    job = parse(
        'job "solo" { datacenters = ["dc1"] '
        'task "t" { driver = "exec" config { command = "/bin/true" } } }'
    )
    assert len(job.task_groups) == 1
    assert job.task_groups[0].name == "t"
    assert job.task_groups[0].count == 1


def test_invalid_key_rejected():
    with pytest.raises(ValueError, match="invalid key"):
        parse('job "x" { bogus_key = true task "t" { driver = "exec" } }')


def test_duration_parsing():
    assert parse_duration("30s") == 30.0
    assert parse_duration("10m") == 600.0
    assert parse_duration("1h30m") == 5400.0
    assert parse_duration("250ms") == 0.25
    assert parse_duration(5) == 5.0
    with pytest.raises(ValueError):
        parse_duration("10 parsecs")


def test_hcl_comments_and_lists():
    out = parse_hcl(
        """
        // line comment
        /* block
           comment */
        key = "value"  # trailing
        nums = [1, 2, 3]
        nested { inner = true }
        repeated { a = 1 }
        repeated { a = 2 }
        """
    )
    assert out["key"] == "value"
    assert out["nums"] == [1, 2, 3]
    assert out["nested"]["inner"] is True
    assert [b["a"] for b in out["repeated"]] == [1, 2]


def test_hcl_errors_carry_line_numbers():
    with pytest.raises(HCLParseError, match="line 2"):
        parse_hcl('ok = 1\nbad = "unterminated')


# --------------------------------------------------- interpolation


def test_env_value_interpolation():
    """Task env values reference NOMAD_* vars (env.go ParseAndReplace)."""
    from nomad_tpu import mock
    from nomad_tpu.client.env import build_task_env

    alloc = mock.alloc()
    task = alloc.job.task_groups[0].tasks[0]
    task.env = {"ADDR": "http://${NOMAD_IP}:8080",
                "WHO": "${NOMAD_TASK_NAME}@${NOMAD_JOB_NAME}",
                "MISSING": "${NOT_A_VAR}"}
    env = build_task_env(alloc, task, "/a", "/t", "/s")
    assert env["WHO"] == f"{task.name}@{alloc.job.name}"
    assert env["ADDR"].startswith("http://") and "${" not in env["ADDR"]
    assert env["MISSING"] == "${NOT_A_VAR}"  # unknown vars stay verbatim


def test_service_name_interpolation():
    from nomad_tpu import mock
    from nomad_tpu.consul import task_services
    from nomad_tpu.structs.job import Service

    alloc = mock.alloc()
    task = alloc.job.task_groups[0].tasks[0]
    task.services = [Service(name="${NOMAD_JOB_NAME}-web",
                             tags=["g-${NOMAD_GROUP_NAME}"],
                             port_label="http")]
    services = task_services(alloc, task)
    assert services[0].name == f"{alloc.job.name}-web"
    assert services[0].tags == [f"g-{alloc.task_group}"]


def test_interpolate_value_recursive():
    from nomad_tpu.utils.interpolate import interpolate_value

    env = {"X": "1", "Y": "2"}
    cfg = {"command": "/bin/${X}", "args": ["${Y}", 3, {"k": "${X}${Y}"}],
           "n": 42}
    out = interpolate_value(cfg, env)
    assert out == {"command": "/bin/1", "args": ["2", 3, {"k": "12"}],
                   "n": 42}
