"""Scheduler executive (nomad_tpu/server/executive.py): the batched
event-loop replacement for thread-per-eval dense scheduling.

The contract under test:

- a storm against a parked drain processes as a FEW cohorts (eval
  identity = batch row), every eval reaches exactly one terminal
  status, and every alloc places exactly once;
- executive-vs-worker placement parity: the same seeded cluster
  commits the same (job, slot) -> node mapping under both drivers
  (same snapshot, same device programs — the tie-break-free cluster
  makes the argmax unique);
- evals whose diff carries non-placement semantics (job updates,
  drains, deregisters) route to the per-eval scheduler's legacy lane
  and still commit correctly;
- capacity exhaustion creates blocked evals that unblock and place
  when nodes arrive (the blocked-eval machinery rides the fast path);
- a device fault falls the cohort back to the host iterators (breaker
  counted), an expired eval terminalizes with the structured reason,
  leadership loss drains accumulated leases back to the broker, and
  the saturation signal backpressures the worker handoff.
"""

import time

import pytest

from nomad_tpu import mock
from nomad_tpu.chaos import FaultSpec, chaos
from nomad_tpu.server import Server, ServerConfig
from nomad_tpu.server.worker import DEQUEUE_TIMEOUT
from nomad_tpu.structs import consts


@pytest.fixture(autouse=True)
def _always_disarm():
    yield
    chaos.disarm()
    from nomad_tpu.admission import get_breaker

    b = get_breaker()
    b.reset()
    b.configure_defaults()


def wait_until(fn, timeout=90.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if fn():
            return True
        time.sleep(interval)
    return False


def make_server(**over):
    defaults = dict(
        num_schedulers=2,
        scheduler_factories={"service": "service-tpu"},
        eval_batch_size=16,
        scheduler_executive=True,
        executive_threads=4,
        eval_nack_timeout=5.0,
        eval_delivery_limit=8,
    )
    defaults.update(over)
    server = Server(ServerConfig(**defaults))
    server.start()
    return server


def seed_nodes(server, n=30, cpu=None, mem=None):
    nodes = []
    for i in range(n):
        node = mock.node()
        if cpu is not None:
            # Distinct capacities -> unique BestFit scores -> the
            # placement argmax is tie-break free (parity tests).
            node.resources.cpu = cpu + i * 10
        if mem is not None:
            node.resources.memory_mb = mem
        node.compute_class()
        server.node_register(node)
        nodes.append(node)
    return nodes


def quiesce(server):
    for w in server.workers:
        w.set_pause(True)
    server.executive.set_pause(True)
    assert wait_until(
        lambda: all(w.parked() for w in server.workers)
        and server.executive.parked(),
        timeout=4 * DEQUEUE_TIMEOUT + 30.0)


def release(server):
    for w in server.workers:
        w.set_pause(False)
    server.executive.set_pause(False)


def make_job(jid, count=5, cpu=20, mem=16, priority=None):
    job = mock.job()
    job.id = jid
    job.task_groups[0].count = count
    if priority is not None:
        job.priority = priority
    t = job.task_groups[0].tasks[0]
    t.resources.cpu = cpu
    t.resources.memory_mb = mem
    t.resources.networks = []
    return job


def run_storm(server, n_jobs, prefix, count=5):
    quiesce(server)
    jobs, evals = [], []
    for i in range(n_jobs):
        job = make_job(f"{prefix}-{i}", count=count)
        ev, _ = server.job_register(job)
        jobs.append(job)
        evals.append(ev)
    assert wait_until(lambda: server.broker.ready_count() >= n_jobs, 15.0)
    release(server)
    return jobs, evals


def settle(server, evals, timeout=120.0):
    state = server.fsm.state

    def done():
        evs = [state.eval_by_id(e) for e in evals]
        return all(e is not None and e.terminal_status() for e in evs)

    assert wait_until(done, timeout), {
        e: getattr(state.eval_by_id(e), "status", None) for e in evals}


def test_drain_cuts_early_when_broker_dry():
    """BENCH_r14's config-5 churn regression (x0.71): with finalize
    tails outstanding the drain accumulated the FULL dispatch window
    even after the broker ran dry — under churn's chained follow-up
    evals that full-window hold compounds per hop. The fix: a cohort
    in hand + an empty bulk drain for dispatch_idle_grace cuts early.
    Driven directly against _drain with a deliberately huge window: a
    3-eval cohort must come back in a fraction of it."""
    # No threads: the drain is called directly (the running executive
    # would race it for the broker's evals otherwise).
    server = Server(ServerConfig(
        num_schedulers=2,
        scheduler_factories={"service": "service-tpu"},
        eval_batch_size=16, scheduler_executive=True,
        dispatch_window=30.0, dispatch_idle_grace=0.02))
    try:
        server.establish_leadership()
        seed_nodes(server, 4)
        for i in range(3):
            server.job_register(make_job(f"dry-{i}", count=2))
        assert server.broker.ready_count() == 3
        # the worker handoff seed (what wakes the drain)
        ev, token = server.broker.dequeue(["service"], timeout=1.0)
        assert ev is not None
        server.executive.submit(ev, token)
        t0 = time.monotonic()
        batch = server.executive._drain(window=30.0)
        elapsed = time.monotonic() - t0
        assert len(batch) == 3
        assert elapsed < 5.0, f"drain held a dry broker {elapsed:.1f}s"
        for entry in batch:
            server.eval_nack(entry.eval.id, entry.token)
    finally:
        server.shutdown()


def test_executive_storm_forms_cohorts_and_places_exactly_once():
    server = make_server()
    try:
        seed_nodes(server)
        jobs, evals = run_storm(server, 12, "storm")
        settle(server, evals)
        for job in jobs:
            live = [a for a in server.fsm.state.allocs_by_job(job.id)
                    if not a.terminal_status()]
            assert len(live) == 5, (job.id, len(live))
            assert len({a.name for a in live}) == 5  # exactly once
        ex = server.executive.stats()
        assert ex["enabled"]
        assert ex["fast_evals"] >= 10, ex
        # Cohorts, not threads: the storm rode a few cohort cuts.
        assert 1 <= ex["cohorts"] <= 4, ex
        assert ex["occupancy"] >= 3, ex
        # The device work went through the no-park cohort dispatch.
        from nomad_tpu.scheduler.batcher import get_batcher

        assert get_batcher().stats()["cohort_dispatches"] >= 1
        # The superseded pipeline never engaged.
        assert not server.dispatch.enabled
    finally:
        server.shutdown()


def _committed_map(server, jobs):
    out = {}
    for job in jobs:
        for a in server.fsm.state.allocs_by_job(job.id):
            if not a.terminal_status():
                out[(a.job_id, a.name)] = a.node_id
    return out


def test_executive_vs_worker_placement_parity():
    """Same seeded cluster + jobs under both drivers -> identical
    committed (job, slot) -> node maps. Placement is FORCED (each job
    rack-pinned to exactly its `count` nodes + distinct_hosts), so the
    map is order/tie-break/conflict-independent — what the test then
    proves is that both drivers commit the same allocs end to end
    (feasibility masks, plan legs, exactly-once terminals), not that
    retry interleavings happen to agree."""
    from nomad_tpu.structs import Constraint

    n_jobs, count = 4, 3

    def run(executive):
        server = make_server(scheduler_executive=executive)
        try:
            rank = {}
            for i in range(n_jobs * count):
                node = mock.node()
                node.meta["rack"] = f"r{i % n_jobs}"
                node.compute_class()
                server.node_register(node)
                rank[node.id] = i
            quiesce(server)
            jobs, evals = [], []
            for j in range(n_jobs):
                job = make_job(f"par-{j}", count=count)
                job.constraints.append(Constraint(
                    ltarget="${meta.rack}", operand="=",
                    rtarget=f"r{j}"))
                job.task_groups[0].constraints.append(
                    Constraint(operand=consts.CONSTRAINT_DISTINCT_HOSTS))
                ev, _ = server.job_register(job)
                jobs.append(job)
                evals.append(ev)
            release(server)
            settle(server, evals)
            committed = _committed_map(server, jobs)
            assert len(committed) == n_jobs * count, committed
            # Slot-name -> node pairing WITHIN a job is PRNG
            # tie-broken among its equivalent rack nodes (independent
            # per-eval streams by design); the driver-level invariant
            # is the committed node SET per job.
            by_job = {}
            for (job_id, _name), node_id in committed.items():
                by_job.setdefault(job_id, set()).add(rank[node_id])
            return {j: frozenset(v) for j, v in by_job.items()}
        finally:
            server.shutdown()

    with_exec = run(True)
    with_workers = run(False)
    assert with_exec == with_workers


def test_job_update_routes_legacy_and_commits():
    server = make_server()
    try:
        seed_nodes(server)
        jobs, evals = run_storm(server, 4, "upd", count=3)
        settle(server, evals)
        base_legacy = server.executive.stats()["legacy_evals"]
        # Destructive update: bump resources -> diff has update bucket.
        quiesce(server)
        ev2 = []
        for job in jobs:
            job2 = make_job(job.id, count=3, cpu=30)
            ev, _ = server.job_register(job2)
            ev2.append(ev)
        release(server)
        settle(server, ev2)
        ex = server.executive.stats()
        assert ex["legacy_evals"] > base_legacy, ex
        assert any("stop/update" in r or "buckets" in r
                   for r in ex["legacy_reasons"]), ex["legacy_reasons"]
        for job in jobs:
            live = [a for a in server.fsm.state.allocs_by_job(job.id)
                    if not a.terminal_status()]
            assert len(live) == 3
    finally:
        server.shutdown()


def test_exhaustion_creates_blocked_evals_that_unblock():
    server = make_server()
    try:
        seed_nodes(server, n=2, cpu=100, mem=256)
        # 8 allocs x 30cpu will not fit 2 tiny nodes.
        jobs, evals = run_storm(server, 1, "blocked", count=8)
        settle(server, evals)
        blocked = [e for e in server.fsm.state.evals()
                   if e.status == consts.EVAL_STATUS_BLOCKED]
        assert blocked, [
            (e.status, e.triggered_by) for e in server.fsm.state.evals()]
        # Capacity arrives -> the blocked eval unblocks and places.
        for _ in range(6):
            node = mock.node()
            node.compute_class()
            server.node_register(node)
        assert wait_until(lambda: len(
            [a for a in server.fsm.state.allocs_by_job(jobs[0].id)
             if not a.terminal_status()]) == 8, 90.0)
    finally:
        server.shutdown()


def test_device_fault_falls_back_to_host_path():
    server = make_server()
    try:
        seed_nodes(server)
        warm_jobs, warm_evals = run_storm(server, 4, "warm")
        settle(server, warm_evals)
        chaos.arm(7, [FaultSpec("binpack.device", "error", count=1)])
        jobs, evals = run_storm(server, 6, "faulted")
        settle(server, evals)
        fired = chaos.firing_log()
        chaos.disarm()
        assert any(s == "binpack.device" for s, _n, _k, _d in fired)
        ex = server.executive.stats()
        assert ex["host_fallbacks"] >= 1, ex
        for job in jobs:
            live = [a for a in server.fsm.state.allocs_by_job(job.id)
                    if not a.terminal_status()]
            assert len(live) == 5
    finally:
        server.shutdown()


def test_expired_eval_terminalizes_structured():
    server = make_server()
    try:
        seed_nodes(server, n=4)
        quiesce(server)
        job = make_job("late", count=4)
        idx = server.log.apply("job_register", {"job": job})
        stored = server.fsm.state.job_by_id(job.id)
        from nomad_tpu.structs.eval import new_eval

        ev = new_eval(stored, consts.EVAL_TRIGGER_JOB_REGISTER)
        # Expires AFTER dequeue, while pending in the parked executive
        # — the accumulation-window leg of deadline enforcement (the
        # broker's dequeue-side check covers already-expired evals).
        ev.deadline = time.time() + 1.0
        ev.modify_index = idx
        server.eval_update([ev])
        got, token = server.broker.dequeue(["service"], timeout=5.0)
        assert got is not None and got.id == ev.id
        server.executive.submit(got, token)
        time.sleep(1.2)
        release(server)
        assert wait_until(lambda: (
            server.fsm.state.eval_by_id(ev.id) is not None
            and server.fsm.state.eval_by_id(ev.id).status
            == consts.EVAL_STATUS_FAILED), 30.0)
        desc = server.fsm.state.eval_by_id(ev.id).status_description
        assert "deadline expired" in desc
        assert server.executive.stats()["expired_dropped"] == 1
    finally:
        server.shutdown()


def test_leadership_loss_drains_accumulated_leases():
    server = make_server()
    try:
        seed_nodes(server, n=6)
        quiesce(server)
        jobs = [make_job(f"dr-{i}", count=4) for i in range(4)]
        evals = [server.job_register(j)[0] for j in jobs]
        # Seed the executive while its drain is parked: entries sit in
        # _pending holding broker leases.
        pairs = []
        while len(pairs) < 4:
            ev, token = server.broker.dequeue(["service"], timeout=5.0)
            assert ev is not None
            pairs.append((ev, token))
        for ev, token in pairs:
            server.executive.submit(ev, token)
        assert server.executive.pending_count() == 4
        drained = server.executive.drain()
        assert drained == 4
        assert server.executive.stats()["nacked"] >= 4
        # The nacks re-readied the evals; release and settle.
        release(server)
        settle(server, evals)
    finally:
        server.shutdown()


def test_saturation_backpressures_worker_handoff():
    server = make_server(eval_batch_size=4)
    try:
        assert not server.executive.saturated()
        quiesce(server)
        seed_nodes(server, n=4)
        jobs = [make_job(f"sat-{i}", count=4) for i in range(9)]
        evals = [server.job_register(j)[0] for j in jobs]
        pairs = []
        while len(pairs) < 8:
            ev, token = server.broker.dequeue(["service"], timeout=5.0)
            assert ev is not None
            pairs.append((ev, token))
        for ev, token in pairs:
            server.executive.submit(ev, token)
        # 2 * max_batch entries held -> the worker drain must nap
        # instead of moving more backlog out of the bounded queues.
        assert server.executive.saturated()
        release(server)
        settle(server, evals)
        assert not server.executive.saturated()
    finally:
        server.shutdown()


def test_executive_stats_surface_and_knobs():
    server = make_server(executive_threads=2)
    try:
        st = server.stats()["scheduler_executive"]
        assert st["enabled"] and st["executive_threads"] == 2
        # knob surface: HCL/CLI map onto ServerConfig fields
        from nomad_tpu.server.config import ServerConfig as SC

        cfg = SC()
        assert cfg.scheduler_executive is False  # legacy default (A/B)
        assert cfg.executive_threads == 4
    finally:
        server.shutdown()
