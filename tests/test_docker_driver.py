"""Docker driver parity against a FAKE docker CLI (docker.go:324-360
and the createContainer/createImage paths): pull policy, registry
auth via ephemeral DOCKER_CONFIG, port-map modes, resource flags,
container modes, and reattach — all asserted from the argv the driver
actually hands the CLI."""

import json
import os
import stat

import pytest

from nomad_tpu.client.drivers.base import TaskContext
from nomad_tpu.client.drivers.docker import DockerDriver
from nomad_tpu.structs import NetworkResource, Port, Resources, Task

FAKE = """#!/usr/bin/env python3
import json, os, sys
with open(os.environ["FAKE_DOCKER_LOG"], "a") as f:
    rec = {"argv": sys.argv[1:]}
    cfg = os.environ.get("DOCKER_CONFIG")
    if cfg:
        try:
            rec["docker_config"] = json.load(
                open(os.path.join(cfg, "config.json")))
        except OSError:
            pass
    f.write(json.dumps(rec) + "\\n")
cmd = sys.argv[1]
if cmd == "version":
    print("99.9"); sys.exit(0)
if cmd == "image":  # image inspect
    sys.exit(0 if os.environ.get("FAKE_DOCKER_HAS_IMAGE") == "1" else 1)
if cmd in ("pull", "load", "rm", "stop", "kill"):
    sys.exit(0)
if cmd == "run":
    print("cafebabe42"); sys.exit(0)
if cmd == "wait":
    print("0"); sys.exit(0)
if cmd == "inspect":
    fmt = sys.argv[sys.argv.index("-f") + 1]
    print("true" if "Running" in fmt else "4242"); sys.exit(0)
sys.exit(0)
"""


@pytest.fixture
def fake_docker(tmp_path, monkeypatch):
    bin_path = tmp_path / "docker"
    bin_path.write_text(FAKE)
    bin_path.chmod(bin_path.stat().st_mode | stat.S_IXUSR)
    log = tmp_path / "docker.log"
    log.write_text("")
    monkeypatch.setenv("NOMAD_DOCKER_BIN", str(bin_path))
    monkeypatch.setenv("FAKE_DOCKER_LOG", str(log))
    monkeypatch.delenv("FAKE_DOCKER_HAS_IMAGE", raising=False)

    def calls():
        return [json.loads(line)
                for line in log.read_text().splitlines() if line]

    return calls


def make_ctx(tmp_path, networks=None):
    return TaskContext(
        alloc_id="a1b2c3d4",
        alloc_dir=str(tmp_path / "alloc"),
        task_dir=str(tmp_path / "task" / "local"),
        task_root=str(tmp_path / "task"),
        env={"NOMAD_PORT_http": "22000"},
        networks=networks or [],
    )


def make_task(**cfg):
    t = Task(name="web", driver="docker",
             config={"image": "redis:3.2", **cfg})
    t.resources = Resources(cpu=512, memory_mb=256)
    return t


def run_argv(calls):
    return next(c["argv"] for c in calls() if c["argv"][0] == "run")


def test_pull_policy_skips_present_pinned_tag(tmp_path, fake_docker,
                                              monkeypatch):
    monkeypatch.setenv("FAKE_DOCKER_HAS_IMAGE", "1")
    h = DockerDriver().start(make_ctx(tmp_path), make_task())
    h.kill()
    cmds = [c["argv"][0] for c in fake_docker()]
    assert "pull" not in cmds, "pinned tag already present must not pull"


def test_pull_policy_pulls_missing_image(tmp_path, fake_docker):
    h = DockerDriver().start(make_ctx(tmp_path), make_task())
    h.kill()
    cmds = [c["argv"][:2] for c in fake_docker() if c["argv"][0] == "pull"]
    assert cmds == [["pull", "redis:3.2"]]


def test_latest_tag_always_pulls(tmp_path, fake_docker, monkeypatch):
    monkeypatch.setenv("FAKE_DOCKER_HAS_IMAGE", "1")
    task = make_task()
    task.config["image"] = "redis:latest"
    h = DockerDriver().start(make_ctx(tmp_path), task)
    h.kill()
    assert any(c["argv"][0] == "pull" for c in fake_docker())


def test_registry_auth_rides_ephemeral_docker_config(tmp_path, fake_docker):
    task = make_task()
    task.config["image"] = "registry.example.com:5000/app:1.0"
    task.config["auth"] = [{
        "username": "u", "password": "p", "email": "e@x.com",
        "server_address": "registry.example.com:5000",
    }]
    h = DockerDriver().start(make_ctx(tmp_path), task)
    h.kill()
    pull = next(c for c in fake_docker() if c["argv"][0] == "pull")
    auths = pull["docker_config"]["auths"]
    assert "registry.example.com:5000" in auths
    import base64
    assert base64.b64decode(
        auths["registry.example.com:5000"]["auth"]) == b"u:p"
    assert auths["registry.example.com:5000"]["email"] == "e@x.com"


def test_load_archives_instead_of_pull(tmp_path, fake_docker):
    (tmp_path / "task" / "local").mkdir(parents=True)
    task = make_task()
    task.config["load"] = ["redis.tar"]
    h = DockerDriver().start(make_ctx(tmp_path), task)
    h.kill()
    loads = [c["argv"] for c in fake_docker() if c["argv"][0] == "load"]
    # Resolved against the task ROOT — where fetch_artifact delivers —
    # not local/ (artifact + load must compose).
    assert loads and loads[0][2].endswith("task/redis.tar")
    assert not any(c["argv"][0] == "pull" for c in fake_docker())


def test_port_map_publishes_and_remaps_env(tmp_path, fake_docker):
    net = NetworkResource(
        ip="10.0.0.5",
        reserved_ports=[Port(label="admin", value=12345)],
        dynamic_ports=[Port(label="http", value=22000)],
    )
    task = make_task()
    task.config["port_map"] = [{"http": 8080}]
    h = DockerDriver().start(make_ctx(tmp_path, networks=[net]), task)
    h.kill()
    argv = run_argv(fake_docker)
    published = [argv[i + 1] for i, a in enumerate(argv) if a == "-p"]
    # Reserved port: 1:1 (no map entry); dynamic http: host->8080.
    assert "10.0.0.5:12345:12345/tcp" in published
    assert "10.0.0.5:12345:12345/udp" in published
    assert "10.0.0.5:22000:8080/tcp" in published
    assert "10.0.0.5:22000:8080/udp" in published
    # The env advertises the CONTAINER port for the mapped label.
    envs = [argv[i + 1] for i, a in enumerate(argv) if a == "-e"]
    assert "NOMAD_PORT_HTTP=8080" in envs


def test_port_map_without_network_fails(tmp_path, fake_docker):
    task = make_task()
    task.config["port_map"] = [{"http": 8080}]
    with pytest.raises(RuntimeError, match="no network interface"):
        DockerDriver().start(make_ctx(tmp_path), task)


def test_resource_and_mode_flags(tmp_path, fake_docker):
    task = make_task()
    task.config.update({
        "network_mode": "host", "ipc_mode": "host", "pid_mode": "host",
        "uts_mode": "host", "hostname": "web1",
        "dns_servers": ["8.8.8.8"], "dns_search_domains": ["example.com"],
        "labels": [{"team": "infra"}], "privileged": True,
        "work_dir": "/srv",
    })
    h = DockerDriver().start(make_ctx(tmp_path), task)
    h.kill()
    argv = run_argv(fake_docker)
    joined = " ".join(argv)
    assert "--cpu-shares 512" in joined
    assert "--memory 256m" in joined
    assert "--network host" in joined
    assert "--ipc host" in joined and "--pid host" in joined
    assert "--uts host" in joined
    assert "--dns 8.8.8.8" in joined
    assert "--dns-search example.com" in joined
    assert "--hostname web1" in joined
    assert "--label team=infra" in joined
    assert "--privileged" in joined
    assert "-w /srv" in joined


def test_reattach_by_container_id(tmp_path, fake_docker):
    drv = DockerDriver()
    h = drv.start(make_ctx(tmp_path), make_task())
    handle_id = h.id()
    h.kill()
    h2 = drv.open(make_ctx(tmp_path), handle_id)
    assert h2 is not None and h2.container_id == "cafebabe42"
    h2.kill()