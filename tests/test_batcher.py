"""Placement batcher tests: concurrent same-shape requests share one
device dispatch, results match the unbatched program, and mixed shapes
keep separate queues (the broker drain-to-batch shim of the north
star)."""

import threading

import jax
import numpy as np
import pytest

from nomad_tpu.ops.binpack import (
    PlacementConfig,
    make_asks,
    make_node_state,
    placement_program_jit,
)
from nomad_tpu.scheduler.batcher import PlacementBatcher


def tiny_inputs(n=128, k=8, g=2, seed=0):
    state = make_node_state(
        capacity=np.tile([4000, 8192, 100000, 150], (n, 1)),
        sched_capacity=np.tile([3900, 7936, 96000, 150], (n, 1)),
        util=np.tile([100.0, 256.0, 4096.0, 0.0], (n, 1)),
        bw_avail=np.full(n, 1000.0),
        bw_used=np.zeros(n),
        ports_free=np.full(n, 40000.0),
        job_count=np.zeros(n, np.int32),
        tg_count=np.zeros((n, g), np.int32),
        feasible=np.ones((n, g), bool),
        node_ok=np.ones(n, bool),
    )
    asks = make_asks(
        resources=np.tile([500, 256, 150, 0], (k, 1)),
        bw=np.full(k, 50.0),
        ports=np.full(k, 2.0),
        tg_index=np.arange(k, dtype=np.int32) % g,
        active=np.ones(k, bool),
        job_distinct_hosts=False,
        tg_distinct_hosts=np.zeros(g, bool),
    )
    return state, asks, jax.random.PRNGKey(seed)


CONFIG = PlacementConfig(anti_affinity_penalty=10.0)


def test_single_request_matches_direct_program():
    batcher = PlacementBatcher(window=0.001)
    state, asks, key = tiny_inputs(seed=3)
    choices, scores = batcher.place(state, asks, key, CONFIG)
    direct_c, direct_s, _ = placement_program_jit(state, asks, key, CONFIG)
    np.testing.assert_array_equal(choices, np.asarray(direct_c))
    np.testing.assert_allclose(scores, np.asarray(direct_s), rtol=1e-5)


def test_concurrent_requests_share_one_dispatch():
    batcher = PlacementBatcher(window=0.25)  # wide window: all join
    results = {}
    errors = []

    def worker(i):
        try:
            state, asks, key = tiny_inputs(seed=i)
            results[i] = batcher.place(state, asks, key, CONFIG)
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert not errors
    assert len(results) == 6
    # all six rode a small number of dispatches (1 ideally; allow 2 for
    # a straggler that missed the window)
    assert batcher.dispatches <= 2
    assert batcher.batched_requests == 6
    # batched results equal the unbatched program per request
    for i in range(6):
        state, asks, key = tiny_inputs(seed=i)
        direct_c, _, _ = placement_program_jit(state, asks, key, CONFIG)
        np.testing.assert_array_equal(results[i][0], np.asarray(direct_c))


def test_place_cohort_matches_direct_program_and_never_parks():
    """The scheduler executive's no-park entry point: a pre-formed
    cohort dispatches inline on the calling thread — results match the
    unbatched program per row, the cohort counter ticks, and no
    dispatcher thread is ever spawned (thread count is flat across the
    call: nothing to park, nothing to convoy)."""
    batcher = PlacementBatcher(window=0.25)
    reqs = []
    for i in range(6):
        state, asks, key = tiny_inputs(seed=100 + i)
        reqs.append((state, asks, key, CONFIG, None))
    before_threads = threading.active_count()
    results = batcher.place_cohort(reqs)
    assert threading.active_count() <= before_threads
    assert len(results) == 6
    for (state, asks, key, _c, _s), (choices, scores) in zip(reqs, results):
        direct_c, direct_s, _ = placement_program_jit(
            state, asks, key, CONFIG)
        np.testing.assert_array_equal(np.asarray(choices),
                                      np.asarray(direct_c))
        np.testing.assert_allclose(np.asarray(scores),
                                   np.asarray(direct_s), rtol=1e-5)
    stats = batcher.stats()
    assert stats["cohort_dispatches"] >= 1
    assert stats["batched_requests"] == 6
    # One shape -> one dispatch for the whole cohort.
    assert stats["dispatches"] == 1


def test_place_cohort_groups_mixed_shapes():
    """Mixed ask shapes cannot share one program: the cohort splits by
    the same shape key place() computes, one inline dispatch each."""
    batcher = PlacementBatcher(window=0.25)
    s1, a1, k1 = tiny_inputs(seed=1)
    s2, a2, k2 = tiny_inputs(n=64, k=4, seed=2)
    results = batcher.place_cohort([
        (s1, a1, k1, CONFIG, None), (s2, a2, k2, CONFIG, None),
        (s1, a1, k1, CONFIG, None)])
    assert len(results) == 3
    assert batcher.stats()["dispatches"] == 2
    for (state, asks, key), (choices, _sc) in zip(
            ((s1, a1, k1), (s2, a2, k2), (s1, a1, k1)), results):
        direct_c, _ds, _ = placement_program_jit(state, asks, key, CONFIG)
        np.testing.assert_array_equal(np.asarray(choices),
                                      np.asarray(direct_c))


def test_mixed_shapes_do_not_batch_together():
    batcher = PlacementBatcher(window=0.05)
    out = {}

    def worker(name, n):
        state, asks, key = tiny_inputs(n=n)
        out[name] = batcher.place(state, asks, key, CONFIG)

    threads = [threading.Thread(target=worker, args=("a", 128)),
               threading.Thread(target=worker, args=("b", 256))]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    assert len(out) == 2
    assert out["a"][0].shape == out["b"][0].shape  # both [K]
    assert batcher.dispatches == 2  # different node buckets: no mixing


def test_dispatch_error_propagates_to_all_requests():
    batcher = PlacementBatcher(window=0.2)

    state, asks, key = tiny_inputs()
    bad_asks = asks._replace(resources=np.asarray([[1.0]]))  # wrong shape

    with pytest.raises(Exception):
        batcher.place(state, bad_asks, key, CONFIG)


def test_tpu_scheduler_uses_batcher():
    """The service-tpu factory's placements flow through the global
    batcher (observability counters move)."""
    from nomad_tpu import mock
    from nomad_tpu.scheduler.batcher import get_batcher
    from nomad_tpu.scheduler.testing import Harness
    from nomad_tpu.structs import consts, new_eval

    batcher = get_batcher()
    before = batcher.batched_requests
    h = Harness(seed=9)
    for _ in range(4):
        n = mock.node()
        n.compute_class()
        h.state.upsert_node(h.next_index(), n)
    job = mock.job()
    job.task_groups[0].count = 4  # >3: below that the host fallback runs
    h.state.upsert_job(h.next_index(), job)
    h.process("service-tpu", new_eval(job, consts.EVAL_TRIGGER_JOB_REGISTER))
    assert len(h.state.allocs_by_job(job.id)) == 4
    assert batcher.batched_requests > before


def test_overflow_beyond_max_batch_all_served():
    """More same-shaped requests than max_batch in one window: the tail
    rides a follow-up dispatch instead of deadlocking its workers."""
    batcher = PlacementBatcher(max_batch=3, window=0.25)
    results = {}

    def worker(i):
        state, asks, key = tiny_inputs(seed=i)
        results[i] = batcher.place(state, asks, key, CONFIG)

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=90)
    assert all(not t.is_alive() for t in threads), "worker deadlocked"
    assert len(results) == 8
    assert batcher.batched_requests == 8
    assert batcher.dispatches >= 3  # ceil(8/3)


def test_pad_ladders():
    """Shape-bucket ladders: every distinct padded size is an XLA
    program, so the ladders must be coarse and deterministic."""
    import numpy as np

    from nomad_tpu.scheduler.batcher import (
        BATCH_BUCKETS,
        ROW_BUCKETS,
        _pad_batch,
        _pad_rows,
    )

    for n in range(1, 65):
        b = _pad_batch(n, 64)
        assert b >= n and (b in BATCH_BUCKETS or b == 64)
    assert _pad_batch(3, 64) == 4
    assert _pad_batch(17, 64) == 64
    assert _pad_batch(100, 64) == 64  # capped at max_batch

    rows = _pad_rows([7, 3, 9])
    assert len(rows) == ROW_BUCKETS[0]
    assert rows.dtype == np.int32
    assert list(rows[:3]) == [7, 3, 9]
    assert (rows[3:] == 7).all()  # padding repeats the FIRST row
    assert len(_pad_rows(list(range(300)))) == ROW_BUCKETS[1]
    # Beyond the ladder: fall back to pow2.
    assert len(_pad_rows(list(range(5000)))) == 8192


def test_place_self_rescues_when_dispatcher_never_runs(monkeypatch):
    """PR 7 regression (ntalint unbounded-wait): place() used to park
    on a bare event.wait() — a dispatcher whose thread failed to spawn
    (Thread.start under OS thread pressure) left its requesters wedged
    forever. The bounded wait now observes the ownerless queue twice
    and claims dispatchership inline (self-rescue)."""
    batcher = PlacementBatcher(window=0.01)
    state, asks, key = tiny_inputs(seed=5)

    real_dispatch = PlacementBatcher._dispatch
    died_once = []

    def flaky(self, shape_key, config, wait_window):
        if not died_once:
            # First dispatcher: its thread "never starts" — the
            # failed-spawn path un-claims the slot and does no work.
            died_once.append(shape_key)
            with self._lock:
                self._dispatchers.pop(shape_key, None)
            return None
        return real_dispatch(self, shape_key, config, wait_window)

    monkeypatch.setattr(PlacementBatcher, "_dispatch", flaky)

    result = {}

    def run():
        result["v"] = batcher.place(state, asks, key, CONFIG)

    t = threading.Thread(target=run, daemon=True)
    t.start()
    t.join(timeout=60)
    assert not t.is_alive(), "place() wedged: self-rescue did not fire"
    assert "v" in result
    choices, scores = result["v"]
    direct_c, direct_s, _ = placement_program_jit(state, asks, key, CONFIG)
    np.testing.assert_array_equal(choices, np.asarray(direct_c))
    np.testing.assert_allclose(scores, np.asarray(direct_s), rtol=1e-5)


def test_spawn_dispatcher_start_failure_unclaims_slot(monkeypatch):
    """Thread.start failing inside _spawn_dispatcher must release the
    dispatcher slot it was counted for — otherwise the queue looks
    owned forever and no self-rescue can trigger."""
    batcher = PlacementBatcher(window=0.01)

    def boom(self):
        raise RuntimeError("can't start new thread")

    monkeypatch.setattr(threading.Thread, "start", boom)
    with batcher._lock:
        batcher._dispatchers["shape"] = 1
    batcher._spawn_dispatcher("shape", CONFIG)
    with batcher._lock:
        assert batcher._dispatchers.get("shape", 0) == 0
