"""Client agent tests: real task execution end-to-end (mirror
client/client_test.go, task_runner_test.go, alloc_runner_test.go)."""

import os
import tempfile
import time

import pytest

from nomad_tpu import mock
from nomad_tpu.api import HTTPServer
from nomad_tpu.client import ClientAgent, ClientConfig
from nomad_tpu.server import Server, ServerConfig
from nomad_tpu.structs import consts


def wait_until(fn, timeout=8.0, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if fn():
            return True
        time.sleep(interval)
    return False


@pytest.fixture
def cluster(tmp_path):
    server = Server(ServerConfig(num_schedulers=1, eval_nack_timeout=5.0))
    server.start()
    http = HTTPServer(server)
    http.start()
    cfg = ClientConfig(
        servers=[http.addr],
        state_dir=str(tmp_path / "state"),
        alloc_dir=str(tmp_path / "allocs"),
        options={"driver.raw_exec.enable": "1"},
        dev_mode=True,
    )
    os.makedirs(cfg.state_dir, exist_ok=True)
    agent = ClientAgent(cfg)
    agent.start()
    yield server, agent
    agent.shutdown(destroy_allocs=True)
    http.stop()
    server.shutdown()


def mock_driver_job(run_for=1e9, exit_code=0, count=1, job_type="service"):
    job = mock.job()
    job.type = job_type
    tg = job.task_groups[0]
    tg.count = count
    task = tg.tasks[0]
    task.driver = "mock_driver"
    task.config = {"run_for": run_for, "exit_code": exit_code}
    task.resources.networks = []
    if job_type == "batch":
        tg.restart_policy.attempts = 0
        tg.restart_policy.mode = "fail"
    return job


def test_client_registers_with_fingerprints(cluster):
    server, agent = cluster
    node = server.fsm.state.node_by_id(agent.node.id)
    assert node is not None
    assert node.status == consts.NODE_STATUS_READY
    assert node.attributes.get("driver.mock_driver") == "1"
    assert node.attributes.get("driver.raw_exec") == "1"
    assert node.attributes.get("kernel.name") == "linux"
    assert node.resources.cpu > 0 and node.resources.memory_mb > 0


def test_service_job_runs_tasks(cluster):
    server, agent = cluster
    job = mock_driver_job()
    server.job_register(job)
    assert wait_until(
        lambda: any(
            a.client_status == consts.ALLOC_CLIENT_RUNNING
            for a in server.fsm.state.allocs_by_job(job.id)
        )
    )
    alloc = server.fsm.state.allocs_by_job(job.id)[0]
    assert alloc.task_states["web"].state == consts.TASK_STATE_RUNNING
    assert server.fsm.state.job_summary_by_id(job.id).summary["web"].running == 1


def test_batch_job_completes(cluster):
    server, agent = cluster
    job = mock_driver_job(run_for=0.2, job_type="batch")
    server.job_register(job)
    assert wait_until(
        lambda: all(
            a.client_status == consts.ALLOC_CLIENT_COMPLETE
            for a in server.fsm.state.allocs_by_job(job.id)
        )
        and len(server.fsm.state.allocs_by_job(job.id)) == 1
    )
    alloc = server.fsm.state.allocs_by_job(job.id)[0]
    assert alloc.task_states["web"].successful()
    assert server.fsm.state.job_by_id(job.id).status == consts.JOB_STATUS_DEAD


def test_raw_exec_runs_real_process(cluster):
    server, agent = cluster
    job = mock_driver_job(job_type="batch")
    task = job.task_groups[0].tasks[0]
    task.driver = "raw_exec"
    task.config = {
        "command": "/bin/sh",
        "args": ["-c", "echo hello-from-$NOMAD_TASK_NAME > $NOMAD_TASK_DIR/out.txt"],
    }
    server.job_register(job)
    assert wait_until(
        lambda: all(
            a.client_status == consts.ALLOC_CLIENT_COMPLETE
            for a in server.fsm.state.allocs_by_job(job.id)
        )
        and len(server.fsm.state.allocs_by_job(job.id)) == 1
    )
    alloc = server.fsm.state.allocs_by_job(job.id)[0]
    runner = agent.alloc_runners[alloc.id]
    out = runner.alloc_dir.read_at(f"web/local/out.txt").decode()
    assert out.strip() == "hello-from-web"
    # stdout/stderr files exist in the shared log dir
    logs = runner.alloc_dir.list_dir("alloc/logs")
    assert any(f["name"] == "web.stdout.0" for f in logs)


def test_failed_task_restarts_then_fails(cluster):
    server, agent = cluster
    job = mock_driver_job(run_for=0.05, exit_code=1, job_type="batch")
    tg = job.task_groups[0]
    tg.restart_policy.attempts = 2
    tg.restart_policy.interval = 60.0
    tg.restart_policy.delay = 0.05
    tg.restart_policy.mode = "fail"
    server.job_register(job)
    assert wait_until(
        lambda: any(
            a.client_status == consts.ALLOC_CLIENT_FAILED
            for a in server.fsm.state.allocs_by_job(job.id)
        ),
        timeout=15.0,
    )
    alloc = next(
        a for a in server.fsm.state.allocs_by_job(job.id)
        if a.client_status == consts.ALLOC_CLIENT_FAILED
    )
    ts = alloc.task_states["web"]
    assert ts.failed
    restarts = [e for e in ts.events if e.type == consts.TASK_EVENT_RESTARTING]
    assert len(restarts) == 2  # the restart budget was consumed
    assert any(e.type == consts.TASK_EVENT_NOT_RESTARTING for e in ts.events)


def test_job_stop_kills_tasks(cluster):
    server, agent = cluster
    job = mock_driver_job()
    server.job_register(job)
    assert wait_until(
        lambda: any(
            a.client_status == consts.ALLOC_CLIENT_RUNNING
            for a in server.fsm.state.allocs_by_job(job.id)
        )
    )
    server.job_deregister(job.id)
    assert wait_until(
        lambda: all(
            a.client_status in (consts.ALLOC_CLIENT_COMPLETE,)
            for a in server.fsm.state.allocs_by_job(job.id)
        ),
        timeout=10.0,
    )


def test_client_state_persists_node_identity(tmp_path):
    server = Server(ServerConfig(num_schedulers=1))
    server.start()
    http = HTTPServer(server)
    http.start()
    try:
        cfg = ClientConfig(
            servers=[http.addr],
            state_dir=str(tmp_path / "st"),
            alloc_dir=str(tmp_path / "al"),
            dev_mode=True,
        )
        os.makedirs(cfg.state_dir, exist_ok=True)
        a1 = ClientAgent(cfg)
        a1.start()
        node_id = a1.node.id
        a1.shutdown()

        a2 = ClientAgent(cfg)
        assert a2.node.id == node_id  # identity restored from disk
        a2.start()
        a2.shutdown()
    finally:
        http.stop()
        server.shutdown()


def test_client_restart_reattaches_tasks(tmp_path):
    """A restarted client reattaches to live executors instead of
    restarting tasks (task_runner.go:189, plugins.go:31)."""
    server = Server(ServerConfig(num_schedulers=1, eval_nack_timeout=5.0))
    server.start()
    http = HTTPServer(server)
    http.start()
    cfg = ClientConfig(
        servers=[http.addr],
        state_dir=str(tmp_path / "state"),
        alloc_dir=str(tmp_path / "allocs"),
        options={"driver.raw_exec.enable": "1"},
        dev_mode=True,
    )
    os.makedirs(cfg.state_dir, exist_ok=True)
    agent = ClientAgent(cfg)
    agent.start()
    try:
        job = mock_driver_job()
        task = job.task_groups[0].tasks[0]
        task.driver = "raw_exec"
        task.config = {"command": "/bin/sh", "args": ["-c", "sleep 600"]}
        server.job_register(job)
        assert wait_until(
            lambda: any(
                a.client_status == consts.ALLOC_CLIENT_RUNNING
                for a in server.fsm.state.allocs_by_job(job.id)
            )
        )
        runner = next(iter(agent.alloc_runners.values()))
        tr = runner.task_runners["web"]
        assert wait_until(lambda: tr.handle is not None)
        pid_before = tr.handle.pid()
        assert pid_before

        # Stop the client without destroying allocs; the executor (own
        # session) keeps the task alive.
        agent.shutdown(destroy_allocs=False)
        os.kill(pid_before, 0)  # still running

        agent2 = ClientAgent(cfg)
        agent2.start()
        try:
            assert agent2.node.id == agent.node.id
            assert wait_until(
                lambda: any(
                    r.task_runners.get("web") is not None
                    and r.task_runners["web"].handle is not None
                    for r in agent2.alloc_runners.values()
                ),
                timeout=15.0,
            )
            runner2 = next(iter(agent2.alloc_runners.values()))
            tr2 = runner2.task_runners["web"]
            assert wait_until(lambda: tr2.handle is not None and tr2.handle.pid() == pid_before)
            # Same pid: the task was adopted, not restarted.
            assert tr2.handle.pid() == pid_before
        finally:
            agent2.shutdown(destroy_allocs=True)
    finally:
        http.stop()
        server.shutdown()


def test_driver_config_interpolation(cluster, tmp_path):
    """${NOMAD_*} vars in driver config are interpolated at start
    (env.go ParseAndReplace through the task runner)."""
    server, agent = cluster
    out_file = tmp_path / "interp.out"
    job = mock_driver_job(job_type="batch")
    task = job.task_groups[0].tasks[0]
    task.driver = "raw_exec"
    task.config = {
        "command": "/bin/sh",
        "args": ["-c", f"echo alloc=${{NOMAD_ALLOC_ID}} > {out_file}"],
    }
    server.job_register(job)
    assert wait_until(
        lambda: all(
            a.client_status == consts.ALLOC_CLIENT_COMPLETE
            for a in server.fsm.state.allocs_by_job(job.id)
        )
        and len(server.fsm.state.allocs_by_job(job.id)) == 1
    )
    alloc = server.fsm.state.allocs_by_job(job.id)[0]
    content = out_file.read_text().strip()
    assert content == f"alloc={alloc.id}"
