"""Direct unit tests for two previously indirectly-covered leader
components: TimeTable (nomad/timetable.go:30 — index<->time ring used
by GC thresholds) and PlanQueue (nomad/plan_queue.go:29 — priority heap
of pending-plan futures, leader-only). Plus the uuid fork-safety hook
and the chunked-streaming HTTP reply path at size."""

import os
import threading
import time

import pytest

from nomad_tpu.server.plan_queue import PlanQueue
from nomad_tpu.server.timetable import TimeTable
from nomad_tpu.structs import Plan, PlanResult


class TestTimeTable:
    def test_witness_and_nearest_index(self):
        tt = TimeTable(granularity=0.0)
        t0 = 1000.0
        for i, dt in ((10, 0.0), (20, 10.0), (30, 20.0)):
            tt.witness(i, t0 + dt)
        assert tt.nearest_index(t0 + 25.0) == 30
        assert tt.nearest_index(t0 + 15.0) == 20
        assert tt.nearest_index(t0 + 5.0) == 10
        assert tt.nearest_index(t0 - 1.0) == 0

    def test_nearest_time(self):
        tt = TimeTable(granularity=0.0)
        tt.witness(10, 1000.0)
        tt.witness(20, 1010.0)
        assert tt.nearest_time(25) == 1010.0
        assert tt.nearest_time(15) == 1000.0
        assert tt.nearest_time(5) == 0.0

    def test_granularity_coalesces(self):
        tt = TimeTable(granularity=5.0)
        tt.witness(1, 1000.0)
        tt.witness(2, 1001.0)  # within granularity: dropped
        tt.witness(3, 1006.0)
        assert tt.nearest_index(1001.0) == 1
        assert tt.nearest_index(1007.0) == 3

    def test_history_limit_trims(self):
        tt = TimeTable(granularity=0.0, limit=10)
        tt.witness(1, 1000.0)
        tt.witness(2, 1020.0)  # 1000.0 is now past the 10s window
        assert tt.nearest_index(1001.0) == 0


class TestPlanQueue:
    def make_plan(self, priority=50):
        plan = Plan()
        plan.priority = priority
        return plan

    def test_disabled_rejects_enqueue(self):
        q = PlanQueue()
        with pytest.raises(Exception):
            q.enqueue(self.make_plan())

    def test_priority_order(self):
        q = PlanQueue()
        q.set_enabled(True)
        lo = q.enqueue(self.make_plan(10))
        hi = q.enqueue(self.make_plan(90))
        assert q.depth() == 2
        first = q.dequeue(timeout=1.0)
        assert first.plan.priority == 90
        assert q.dequeue(timeout=1.0).plan.priority == 10

    def test_future_resolves_waiter(self):
        q = PlanQueue()
        q.set_enabled(True)
        pending = q.enqueue(self.make_plan())
        got = {}

        def waiter():
            got["result"] = pending.wait(timeout=5.0)

        t = threading.Thread(target=waiter)
        t.start()
        applier_side = q.dequeue(timeout=1.0)
        result = PlanResult()
        applier_side.respond(result, None)
        t.join(timeout=5.0)
        assert got["result"] is result

    def test_future_propagates_error(self):
        q = PlanQueue()
        q.set_enabled(True)
        pending = q.enqueue(self.make_plan())
        q.dequeue(timeout=1.0).respond(None, RuntimeError("boom"))
        with pytest.raises(RuntimeError, match="boom"):
            pending.wait(timeout=5.0)

    def test_disable_flushes(self):
        q = PlanQueue()
        q.set_enabled(True)
        pending = q.enqueue(self.make_plan())
        q.set_enabled(False)
        # The parked plan fails rather than hanging its worker forever.
        with pytest.raises(Exception):
            pending.wait(timeout=5.0)
        assert q.depth() == 0

    def test_dequeue_timeout_returns_none(self):
        q = PlanQueue()
        q.set_enabled(True)
        assert q.dequeue(timeout=0.05) is None


def test_generate_uuid_fork_safe():
    """A forked child must not replay the parent's buffered entropy
    (utils/ids.py register_at_fork hook)."""
    from nomad_tpu.utils.ids import generate_uuid

    generate_uuid()  # warm the parent's batch buffer
    r, w = os.pipe()
    pid = os.fork()
    if pid == 0:  # child
        os.close(r)
        ids = ",".join(generate_uuid() for _ in range(8))
        os.write(w, ids.encode())
        os.close(w)
        os._exit(0)
    os.close(w)
    child_ids = os.read(r, 65536).decode().split(",")
    os.close(r)
    os.waitpid(pid, 0)
    parent_ids = [generate_uuid() for _ in range(8)]
    assert not (set(child_ids) & set(parent_ids)), "fork replayed entropy"


def test_chunked_stream_reply_large_payload():
    """A multi-megabyte streamed RawResponse survives HTTP chunked
    framing intact (the sticky-disk snapshot path at size)."""
    import urllib.request

    from nomad_tpu.api import HTTPServer
    from nomad_tpu.api.http import RawResponse
    from nomad_tpu.server import Server, ServerConfig

    blob = os.urandom(3 * 1024 * 1024)

    srv = Server(ServerConfig(num_schedulers=0))
    srv.start()
    http = HTTPServer(srv)

    def fake_route(method, query, body):
        def stream(w):
            for off in range(0, len(blob), 65536):
                w.write(blob[off:off + 65536])
        return RawResponse(stream=stream, content_type="application/x-tar")

    orig_handle = http.handle

    def handle(req):
        if req.path == "/stream-test":
            return fake_route(None, None, None)
        return orig_handle(req)

    http.handle = handle
    http.start()
    try:
        with urllib.request.urlopen(http.addr + "/stream-test",
                                    timeout=30) as resp:
            data = resp.read()
        assert data == blob
    finally:
        http.stop()
        srv.shutdown()
