"""HTTP Vault provider tests: the real wire path (VERDICT r2 missing
#1) — token create/renew/revoke-by-accessor over Vault's HTTP API
against an in-process fake vault, the server's own-token renewal loop,
and the full server derive→renew→revoke lifecycle running through the
HTTP provider instead of the stub (reference: nomad/vault.go:1-844)."""

import time

import pytest

from nomad_tpu import mock
from nomad_tpu.server import Server, ServerConfig
from nomad_tpu.server.vault import (
    FakeVaultServer,
    HTTPVaultProvider,
    VaultError,
)
from nomad_tpu.structs import Vault


def wait_until(fn, timeout=8.0, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if fn():
            return True
        time.sleep(interval)
    return False


@pytest.fixture
def vault():
    fake = FakeVaultServer().start()
    yield fake
    fake.stop()


def provider(fake, **kw):
    return HTTPVaultProvider(fake.address, fake.root_token, **kw)


class TestHTTPProvider:
    def test_create_token_over_http(self, vault):
        p = provider(vault)
        token, accessor, ttl = p.create_token(["web-read"])
        assert token and accessor and ttl > 0
        assert vault.tokens_created == 1
        assert vault.store.lookup(token) == ["web-read"]

    def test_root_policy_rejected_client_side(self, vault):
        with pytest.raises(VaultError, match="root"):
            provider(vault).create_token(["root"])
        assert vault.tokens_created == 0

    def test_allowed_policies_enforced(self, vault):
        p = provider(vault, allowed_policies=["a"])
        p.create_token(["a"])
        with pytest.raises(VaultError, match="not allowed"):
            p.create_token(["b"])

    def test_renew_over_http(self, vault):
        p = provider(vault)
        token, _, _ = p.create_token(["p"])
        assert p.renew_token(token) > 0
        assert vault.renews == 1
        with pytest.raises(VaultError):
            p.renew_token("s.bogus")

    def test_revoke_by_accessor(self, vault):
        p = provider(vault)
        token, accessor, _ = p.create_token(["p"])
        p.revoke_tokens([accessor])
        assert vault.store.lookup(token) is None
        # Idempotent: revoking again (unknown accessor) is not an error.
        p.revoke_tokens([accessor])
        assert vault.revokes == 1

    def test_bad_own_token_denied(self, vault):
        p = HTTPVaultProvider(vault.address, "s.wrong")
        with pytest.raises(VaultError, match="403|permission"):
            p.create_token(["p"])

    def test_validate_looks_up_self(self, vault):
        data = provider(vault).validate()
        assert "root" in data["policies"]

    def test_unreachable_vault_raises(self):
        p = HTTPVaultProvider("127.0.0.1:1", "s.x", timeout=0.5)
        with pytest.raises(VaultError):
            p.create_token(["p"])

    def test_self_renewal_loop(self, vault):
        p = provider(vault, ttl=1.0)  # half-life 0.5s
        p.start_renewal()
        try:
            assert wait_until(lambda: vault.self_renews >= 2, timeout=8.0)
        finally:
            p.stop()


class TestServerWithHTTPVault:
    """The server-side lifecycle running over the wire (the round-2 gap:
    every derive/renew/revoke test ran against the in-memory stub)."""

    @pytest.fixture
    def cluster(self, vault):
        srv = Server(ServerConfig(
            num_schedulers=0,
            vault_addr=vault.address,
            vault_token=vault.root_token,
        ))
        srv.start()
        yield srv, vault
        srv.shutdown()

    def seed(self, srv, policies=("web-read",)):
        node = mock.node()
        node.secret_id = "node-secret"
        srv.node_register(node)
        job = mock.job()
        task = job.task_groups[0].tasks[0]
        task.vault = Vault(policies=list(policies))
        alloc = mock.alloc()
        alloc.node_id = node.id
        alloc.job = job
        alloc.job_id = job.id
        alloc.task_group = job.task_groups[0].name
        from nomad_tpu.server import fsm as fsm_msgs

        srv.log.apply(fsm_msgs.ALLOC_UPDATE, {"allocs": [alloc], "job": job})
        return node, job, alloc

    def test_server_uses_http_provider(self, cluster):
        srv, fake = cluster
        assert isinstance(srv.vault, HTTPVaultProvider)

    def test_derive_renew_revoke_over_http(self, cluster):
        srv, fake = cluster
        node, job, alloc = self.seed(srv)
        task_name = job.task_groups[0].tasks[0].name
        tokens, ttl = srv.derive_vault_token(
            node.id, "node-secret", alloc.id, [task_name])
        assert ttl > 0 and fake.tokens_created == 1
        assert fake.store.lookup(tokens[task_name]) == ["web-read"]
        # Renewal via the server RPC surface.
        assert srv.vault_renew(tokens[task_name]) > 0
        assert fake.renews == 1
        # GC revokes the accessor over the wire.
        accs = srv.fsm.state.vault_accessors_by_alloc(alloc.id)
        srv.revoke_vault_accessors([a.accessor for a in accs])
        assert fake.store.lookup(tokens[task_name]) is None
        assert fake.revokes == 1

    def test_partial_mint_failure_revokes_over_http(self, cluster):
        """Second task's mint fails: the first minted token must be
        revoked through the HTTP API (vault.go CreateToken rollback)."""
        srv, fake = cluster
        node, job, alloc = self.seed(srv)
        task_name = job.task_groups[0].tasks[0].name
        with pytest.raises((ValueError, VaultError)):
            srv.derive_vault_token(
                node.id, "node-secret", alloc.id, [task_name, "no-such-task"])
        # Rolled back: nothing live, revocation went over the wire.
        assert all(
            fake.store.lookup(t) is None
            for t in list(fake.store._by_token)
        ) or not fake.store._by_token
        assert fake.revokes >= 1
