"""Latency-aware factory routing: with a dense (TPU) factory
configured, a LONE eval runs on the host iterator pipeline
(millisecond latency — it must not pay the batch window + device RTT),
while a drained batch runs dense and coalesces into shared device
dispatches. VERDICT r2 ask #8."""

import time

import pytest

from nomad_tpu import mock
from nomad_tpu.scheduler.batcher import get_batcher
from nomad_tpu.server import Server, ServerConfig
from nomad_tpu.server.worker import host_factory, is_dense_factory


def wait_until(fn, timeout=30.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if fn():
            return True
        time.sleep(interval)
    return False


def make_server(**over):
    cfg = ServerConfig(
        num_schedulers=1,
        scheduler_factories={"service": "service-tpu"},
        eval_batch_size=16,
        **over,
    )
    server = Server(cfg)
    server.start()
    return server


def seed_nodes(server, n=8):
    for _ in range(n):
        node = mock.node()
        node.compute_class()
        server.node_register(node)


def test_host_factory_mapping():
    assert host_factory("service-tpu") == "service"
    assert host_factory("batch-tpu") == "batch"
    assert host_factory("service") == "service"
    assert is_dense_factory("system-tpu")
    assert not is_dense_factory("system")


def test_lone_eval_routes_to_host_path():
    """One job registered on an idle broker: placements must NOT go
    through the device batcher."""
    server = make_server()
    try:
        seed_nodes(server)
        batcher = get_batcher()
        before = batcher.batched_requests
        job = mock.job()
        job.task_groups[0].count = 3
        server.job_register(job)
        assert wait_until(
            lambda: len(server.fsm.state.allocs_by_job(job.id)) == 3)
        # Placed by the host pipeline: zero new batcher traffic.
        assert batcher.batched_requests == before
    finally:
        server.shutdown()


def test_eval_storm_routes_to_dense_path():
    """Many ready evals drain as one batch and ride the device
    batcher."""
    server = make_server()
    try:
        seed_nodes(server)
        batcher = get_batcher()
        before_req = batcher.batched_requests
        for w in server.workers:
            w.set_pause(True)
        jobs = []
        for _ in range(6):
            job = mock.job()
            job.task_groups[0].count = 5  # >3 so the dense path engages
            server.job_register(job)
            jobs.append(job)
        assert wait_until(lambda: server.broker.ready_count() >= 6)
        for w in server.workers:
            w.set_pause(False)
        assert wait_until(
            lambda: all(
                len(server.fsm.state.allocs_by_job(j.id)) == 5 for j in jobs),
            timeout=60.0,
        )
        # The drained batch went dense: batcher served its requests.
        assert batcher.batched_requests > before_req
    finally:
        server.shutdown()


def test_dense_min_batch_one_forces_dense():
    """Operators can force the dense path for every eval."""
    server = make_server(dense_min_batch=1)
    try:
        seed_nodes(server)
        batcher = get_batcher()
        before = batcher.batched_requests
        job = mock.job()
        job.task_groups[0].count = 6  # >3: small-K host fallback skipped
        server.job_register(job)
        assert wait_until(
            lambda: len(server.fsm.state.allocs_by_job(job.id)) == 6,
            timeout=60.0,
        )
        assert batcher.batched_requests > before
    finally:
        server.shutdown()
