"""Latency-aware factory routing: with a dense (TPU) factory
configured, a LONE eval runs on the host iterator pipeline
(millisecond latency — it must not pay the batch window + device RTT),
while a drained batch runs dense and coalesces into shared device
dispatches. VERDICT r2 ask #8."""

import time

import pytest

from nomad_tpu import mock
from nomad_tpu.scheduler.batcher import get_batcher
from nomad_tpu.server import Server, ServerConfig
from nomad_tpu.server.worker import host_factory, is_dense_factory


def wait_until(fn, timeout=30.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if fn():
            return True
        time.sleep(interval)
    return False


def make_server(**over):
    cfg = ServerConfig(
        num_schedulers=1,
        scheduler_factories={"service": "service-tpu"},
        eval_batch_size=16,
        **over,
    )
    server = Server(cfg)
    server.start()
    return server


def seed_nodes(server, n=8):
    for _ in range(n):
        node = mock.node()
        node.compute_class()
        server.node_register(node)


def test_host_factory_mapping():
    assert host_factory("service-tpu") == "service"
    assert host_factory("batch-tpu") == "batch"
    assert host_factory("service") == "service"
    assert is_dense_factory("system-tpu")
    assert not is_dense_factory("system")
    # Kernel-pinned dense variants (nomad_tpu/kernels) fall back to
    # the SAME host factory: the kernel infix strips with the suffix.
    assert host_factory("service-convex-tpu") == "service"
    assert host_factory("batch-greedy-tpu") == "batch"
    assert is_dense_factory("service-convex-tpu")


def test_tpu_suffix_fallback_registers_lazily():
    """scheduler/__init__.py:52: an unregistered `*-tpu` name triggers
    lazy TPU-factory registration (including every kernel's pinned
    variants) instead of failing — and a name that is neither
    registered nor a -tpu factory fails loudly."""
    import logging

    import pytest as _pytest

    from nomad_tpu import scheduler as sched_mod
    from nomad_tpu.scheduler.testing import Harness

    # Force the lazy path even if another test already registered the
    # dense factories in this process.
    for name in [n for n in sched_mod.scheduler_names()
                 if n.endswith("-tpu")]:
        sched_mod._BUILTIN.pop(name)
    h = Harness()
    logger = logging.getLogger("test")

    s = sched_mod.new_scheduler("service-tpu", logger, h.snapshot(), h)
    assert type(s).__name__ == "BatchedTPUScheduler"
    assert s.kernel is None  # defers to the process-global kernel
    # Kernel-pinned variant, also via the fallback.
    for name in [n for n in sched_mod.scheduler_names()
                 if n.endswith("-tpu")]:
        sched_mod._BUILTIN.pop(name)
    s2 = sched_mod.new_scheduler(
        "batch-convex-tpu", logger, h.snapshot(), h)
    assert type(s2).__name__ == "BatchedTPUScheduler"
    assert s2.kernel == "convex"
    assert s2.batch is True

    with _pytest.raises(ValueError, match="unknown scheduler"):
        sched_mod.new_scheduler("service-xyz", logger, h.snapshot(), h)
    # An unknown KERNEL variant: the -tpu fallback registers the real
    # kernels, the typo'd name stays unknown and fails loudly.
    with _pytest.raises(ValueError, match="unknown scheduler"):
        sched_mod.new_scheduler(
            "service-convexx-tpu", logger, h.snapshot(), h)


def test_unknown_placement_kernel_fails_at_server_init():
    """A typo'd `placement_kernel` must abort Server construction with
    the registered-kernel list — not surface at the first eval."""
    import pytest as _pytest

    from nomad_tpu.kernels import active_kernel, configure

    before = active_kernel()
    try:
        with _pytest.raises(ValueError, match="unknown placement kernel"):
            Server(ServerConfig(num_schedulers=1,
                                placement_kernel="convexx"))
        # The valid names configure cleanly (no server needed).
        configure("convex")
        assert active_kernel() == "convex"
        configure("greedy")
    finally:
        configure(before)


def test_placement_kernel_knob_reaches_stats_surface():
    """ServerConfig.placement_kernel = "convex" routes dense evals
    through the convex kernel, and the quality scoreboard surfaces it
    in server.stats()["placement_quality"]."""
    from nomad_tpu.kernels import active_kernel, configure
    from nomad_tpu.kernels.quality import get_board

    before = active_kernel()
    get_board().reset()
    server = make_server(placement_kernel="convex")
    try:
        seed_nodes(server)
        for w in server.workers:
            w.set_pause(True)
        jobs = []
        for _ in range(4):
            job = mock.job()
            job.task_groups[0].count = 5  # >3 so the dense path engages
            server.job_register(job)
            jobs.append(job)
        assert wait_until(lambda: server.broker.ready_count() >= 4)
        for w in server.workers:
            w.set_pause(False)
        assert wait_until(
            lambda: all(
                len(server.fsm.state.allocs_by_job(j.id)) == 5
                for j in jobs),
            timeout=60.0,
        )
        pq = server.stats()["placement_quality"]
        assert "convex" in pq["kernels"], pq
        entry = pq["kernels"]["convex"]
        assert entry["samples"] > 0
        assert 0.0 <= entry["fragmentation"] <= 1.0
        assert 0.0 <= entry["binpack_score"] <= 1.0
        assert "queueing_delay_ms" in pq
    finally:
        server.shutdown()
        configure(before)


def test_lone_eval_routes_to_host_path():
    """One job registered on an idle broker: placements must NOT go
    through the device batcher."""
    server = make_server()
    try:
        seed_nodes(server)
        batcher = get_batcher()
        before = batcher.batched_requests
        job = mock.job()
        job.task_groups[0].count = 3
        server.job_register(job)
        assert wait_until(
            lambda: len(server.fsm.state.allocs_by_job(job.id)) == 3)
        # Placed by the host pipeline: zero new batcher traffic.
        assert batcher.batched_requests == before
    finally:
        server.shutdown()


def test_eval_storm_routes_to_dense_path():
    """Many ready evals drain as one batch and ride the device
    batcher."""
    server = make_server()
    try:
        seed_nodes(server)
        batcher = get_batcher()
        before_req = batcher.batched_requests
        for w in server.workers:
            w.set_pause(True)
        jobs = []
        for _ in range(6):
            job = mock.job()
            job.task_groups[0].count = 5  # >3 so the dense path engages
            server.job_register(job)
            jobs.append(job)
        assert wait_until(lambda: server.broker.ready_count() >= 6)
        for w in server.workers:
            w.set_pause(False)
        assert wait_until(
            lambda: all(
                len(server.fsm.state.allocs_by_job(j.id)) == 5 for j in jobs),
            timeout=60.0,
        )
        # The drained batch went dense: batcher served its requests.
        assert batcher.batched_requests > before_req
    finally:
        server.shutdown()


def test_dense_min_batch_one_forces_dense():
    """Operators can force the dense path for every eval."""
    server = make_server(dense_min_batch=1)
    try:
        seed_nodes(server)
        batcher = get_batcher()
        before = batcher.batched_requests
        job = mock.job()
        job.task_groups[0].count = 6  # >3: small-K host fallback skipped
        server.job_register(job)
        assert wait_until(
            lambda: len(server.fsm.state.allocs_by_job(job.id)) == 6,
            timeout=60.0,
        )
        assert batcher.batched_requests > before
    finally:
        server.shutdown()
