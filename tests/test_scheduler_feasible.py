"""Feasibility/ranking iterator tests (mirror scheduler/feasible_test.go,
rank_test.go, select_test.go)."""

import random

from nomad_tpu import mock
from nomad_tpu.scheduler.context import EvalContext
from nomad_tpu.scheduler.feasible import (
    ConstraintChecker,
    DriverChecker,
    FeasibilityWrapper,
    ProposedAllocConstraintIterator,
    StaticIterator,
    check_constraint,
    resolve_constraint_target,
)
from nomad_tpu.scheduler.rank import (
    BinPackIterator,
    FeasibleRankIterator,
    JobAntiAffinityIterator,
    RankedNode,
    StaticRankIterator,
)
from nomad_tpu.scheduler.select import LimitIterator, MaxScoreIterator
from nomad_tpu.state import StateStore
from nomad_tpu.structs import Constraint, Plan, Resources, consts


def make_ctx(state=None, plan=None, seed=1):
    state = state or StateStore().snapshot()
    plan = plan or Plan()
    return EvalContext(state, plan, rng=random.Random(seed))


def test_static_iterator():
    ctx = make_ctx()
    nodes = [mock.node() for _ in range(3)]
    it = StaticIterator(ctx, nodes)
    out = [it.next() for _ in range(3)]
    assert out == nodes
    assert it.next() is None
    assert ctx.metrics.nodes_evaluated == 3


def test_static_iterator_wraparound_after_reset():
    ctx = make_ctx()
    nodes = [mock.node() for _ in range(3)]
    it = StaticIterator(ctx, nodes)
    it.next()
    it.reset()
    seen = {it.next().id for _ in range(3)}
    assert len(seen) == 3  # wraps to cover all nodes once per pass


def test_driver_checker():
    ctx = make_ctx()
    n = mock.node()
    c = DriverChecker(ctx, {"exec"})
    assert c.feasible(n)
    c.set_drivers({"docker"})
    assert not c.feasible(n)
    n2 = mock.node()
    n2.attributes["driver.docker"] = "0"
    c2 = DriverChecker(ctx, {"docker"})
    assert not c2.feasible(n2)


def test_resolve_constraint_target():
    n = mock.node()
    assert resolve_constraint_target("${node.unique.id}", n) == (n.id, True)
    assert resolve_constraint_target("${node.datacenter}", n) == ("dc1", True)
    assert resolve_constraint_target("${node.class}", n) == (n.node_class, True)
    assert resolve_constraint_target("${attr.kernel.name}", n) == ("linux", True)
    assert resolve_constraint_target("${meta.pci-dss}", n) == ("true", True)
    assert resolve_constraint_target("${attr.nope}", n)[1] is False
    assert resolve_constraint_target("literal", n) == ("literal", True)


def test_check_constraint_operands():
    ctx = make_ctx()
    assert check_constraint(ctx, "=", "a", "a")
    assert not check_constraint(ctx, "!=", "a", "a")
    assert check_constraint(ctx, "<", "a", "b")
    assert check_constraint(ctx, ">=", "b", "b")
    assert check_constraint(ctx, "version", "1.2.3", ">= 1.0, < 2.0")
    assert not check_constraint(ctx, "version", "2.1.0", ">= 1.0, < 2.0")
    assert check_constraint(ctx, "version", "1.4.0", "~> 1.2")
    assert check_constraint(ctx, "regexp", "linux-x64", "^linux")
    assert not check_constraint(ctx, "regexp", "windows", "^linux")
    # distinct_hosts passes through (handled elsewhere)
    assert check_constraint(ctx, "distinct_hosts", "x", "y")
    assert not check_constraint(ctx, "bogus-op", "x", "y")


def test_constraint_checker():
    ctx = make_ctx()
    n = mock.node()
    c = ConstraintChecker(
        ctx, [Constraint("${attr.kernel.name}", "linux", "=")]
    )
    assert c.feasible(n)
    c.set_constraints([Constraint("${attr.kernel.name}", "darwin", "=")])
    assert not c.feasible(n)
    assert ctx.metrics.nodes_filtered == 1
    # unresolvable target fails closed
    c.set_constraints([Constraint("${attr.missing}", "x", "=")])
    assert not c.feasible(n)


def test_distinct_hosts_iterator():
    store = StateStore()
    job = mock.job()
    job.constraints.append(Constraint(operand="distinct_hosts"))
    n1, n2 = mock.node(), mock.node()
    store.upsert_node(1, n1)
    store.upsert_node(2, n2)
    a = mock.alloc()
    a.job_id = job.id
    a.job = job
    a.node_id = n1.id
    store.upsert_allocs(3, [a])

    ctx = make_ctx(state=store.snapshot())
    src = StaticIterator(ctx, [store.node_by_id(n1.id), store.node_by_id(n2.id)])
    it = ProposedAllocConstraintIterator(ctx, src)
    it.set_job(job)
    it.set_task_group(job.task_groups[0])
    out = []
    while (n := it.next()) is not None:
        out.append(n.id)
    assert out == [n2.id]  # n1 already hosts an alloc for this job


def test_feasibility_wrapper_memoizes_tg_by_class():
    ctx = make_ctx()
    nodes = [mock.node() for _ in range(10)]  # all same computed class

    job_calls, tg_calls = [], []

    class CountingChecker:
        def __init__(self, sink):
            self.sink = sink

        def feasible(self, node):
            self.sink.append(node.id)
            return True

    src = StaticIterator(ctx, nodes)
    w = FeasibilityWrapper(
        ctx, src, [CountingChecker(job_calls)], [CountingChecker(tg_calls)]
    )
    ctx.eligibility.set_job(mock.job())
    w.set_task_group("web")
    for _ in range(10):
        assert w.next() is not None
    # TG checks memoize per computed class (only the first node runs them);
    # job checks run per node, matching reference feasible.go:512-540.
    assert len(tg_calls) == 1
    assert len(job_calls) == 10


def test_feasibility_wrapper_ineligible_class_filtered():
    ctx = make_ctx()
    nodes = [mock.node() for _ in range(5)]

    class FailChecker:
        def feasible(self, node):
            return False

    src = StaticIterator(ctx, nodes)
    w = FeasibilityWrapper(ctx, src, [FailChecker()], [])
    ctx.eligibility.set_job(mock.job())
    w.set_task_group("web")
    assert w.next() is None
    # 4 of 5 were filtered by the class memo without running the checker
    assert ctx.metrics.nodes_filtered >= 4


def test_binpack_scores_and_exhaustion():
    store = StateStore()
    n1 = mock.node()
    store.upsert_node(1, n1)
    ctx = make_ctx(state=store.snapshot())
    job = mock.job()
    tg = job.task_groups[0]

    src = StaticRankIterator(ctx, [RankedNode(store.node_by_id(n1.id))])
    bp = BinPackIterator(ctx, src, evict=False, priority=50)
    bp.set_task_group(tg)
    option = bp.next()
    assert option is not None
    assert option.score > 0
    assert "web" in option.task_resources
    # the network offer was materialized
    assert option.task_resources["web"].networks[0].dynamic_ports[0].value > 0

    # Ask for more than the node has -> exhausted
    big = tg.copy()
    big.tasks[0].resources.cpu = 100000
    src2 = StaticRankIterator(ctx, [RankedNode(store.node_by_id(n1.id))])
    bp2 = BinPackIterator(ctx, src2, evict=False, priority=50)
    bp2.set_task_group(big)
    assert bp2.next() is None
    assert ctx.metrics.nodes_exhausted == 1


def test_job_anti_affinity():
    store = StateStore()
    n1 = mock.node()
    store.upsert_node(1, n1)
    job = mock.job()
    a = mock.alloc()
    a.job_id = job.id
    a.node_id = n1.id
    store.upsert_allocs(2, [a])

    ctx = make_ctx(state=store.snapshot())
    src = StaticRankIterator(ctx, [RankedNode(store.node_by_id(n1.id))])
    it = JobAntiAffinityIterator(ctx, src, 10.0, job.id)
    option = it.next()
    assert option.score == -10.0


def test_limit_and_max_score():
    ctx = make_ctx()
    ranked = [RankedNode(mock.node()) for _ in range(5)]
    for i, r in enumerate(ranked):
        r.score = float(i)
    src = StaticRankIterator(ctx, ranked)
    lim = LimitIterator(ctx, src, 3)
    ms = MaxScoreIterator(ctx, lim)
    best = ms.next()
    assert best.score == 2.0  # only first 3 visited
    assert ms.next() is None
    ms.reset()
    best2 = ms.next()
    assert best2 is not None
