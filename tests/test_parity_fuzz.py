"""Randomized CPU/TPU differential parity (seeded, deterministic):
for a spread of generated clusters and jobs, the host iterator factory
and the dense factory must place the same NUMBER of allocations,
queue the same remainders, and produce plans that survive the plan
applier's AllocsFit verification — the BASELINE acceptance invariant
("identical plan-apply success rate"), swept over shapes no
hand-written scenario covers."""

import random

import pytest

from nomad_tpu import mock
from nomad_tpu.scheduler.testing import Harness
from nomad_tpu.structs import Constraint, allocs_fit, consts, new_eval, remove_allocs


def build_scenario(seed):
    """A (node-set builder, job) pair from one RNG seed. The node list
    (and any pre-existing load) is built ONCE and shared by both
    harnesses — the store copies on upsert — so the two paths see
    byte-identical clusters."""
    rng = random.Random(seed)
    n_nodes = rng.choice([3, 5, 9, 17, 33])
    dc_count = rng.choice([1, 2])
    use_networks = rng.random() < 0.5
    use_racks = rng.random() < 0.4
    distinct = rng.random() < 0.3
    preload = rng.random() < 0.4  # existing allocs consuming capacity
    job_type = rng.choice(["service", "batch"])
    count = rng.choice([1, 2, 5, 12, 40])
    cpu = rng.choice([100, 333, 900])
    mem = rng.choice([64, 300, 700])

    nodes = []
    for i in range(n_nodes):
        node = mock.node()
        node.datacenter = f"dc{i % dc_count + 1}"
        if use_racks:
            node.meta["rack"] = f"r{i % 4}"
        # Heterogeneous capacity: some nodes half-size.
        if i % 3 == 0:
            node.resources.cpu //= 2
            node.resources.memory_mb //= 2
        node.compute_class()
        nodes.append(node)
    filler_allocs = []
    if preload:
        filler = mock.job()
        filler.id = "filler"
        for i, node in enumerate(nodes):
            if i % 2:
                continue
            a = mock.alloc()
            a.node_id, a.job_id, a.job = node.id, filler.id, filler
            a.desired_status = consts.ALLOC_DESIRED_RUN
            a.client_status = consts.ALLOC_CLIENT_RUNNING
            for tr in a.task_resources.values():
                tr.cpu = rng.choice([200, 700])
                tr.memory_mb = rng.choice([128, 512])
                tr.networks = []
            a.resources = None
            filler_allocs.append(a)

    def seed_state(h):
        for node in nodes:
            h.state.upsert_node(h.next_index(), node)
        if filler_allocs:
            h.state.upsert_allocs(h.next_index(), filler_allocs)

    job = mock.job()
    job.type = job_type
    job.datacenters = [f"dc{d + 1}" for d in range(dc_count)]
    tg = job.task_groups[0]
    tg.count = count
    task = tg.tasks[0]
    task.resources.cpu = cpu
    task.resources.memory_mb = mem
    if not use_networks:
        task.resources.networks = []
    if use_racks and rng.random() < 0.5:
        job.constraints.append(Constraint(
            ltarget="${meta.rack}", operand="regexp", rtarget="^r[01]$"))
    if distinct:
        job.constraints.append(Constraint(operand="distinct_hosts"))
    return seed_state, job


def verify_plan(h, snap_before):
    """Every node's proposed alloc set must fit — what the plan
    applier checks before commit (plan_apply.go evaluateNodePlan)."""
    for plan in h.plans:
        for node_id, placed in plan.node_allocation.items():
            node = snap_before.node_by_id(node_id)
            existing = snap_before.allocs_by_node_terminal(node_id, False)
            updates = plan.node_update.get(node_id, [])
            proposed = remove_allocs(existing, updates) + placed
            for a in proposed:
                if a.job is None:
                    a.job = plan.job
            fit, dim, _ = allocs_fit(node, proposed)
            assert fit, f"plan failed verification on {node_id}: {dim}"


@pytest.mark.parametrize("seed", range(300, 316))
def test_randomized_system_parity_with_drains(seed):
    """System jobs (pinned placement) under random drains and loads:
    host vs dense must place identical counts on identical node sets
    and both verify."""
    rng = random.Random(seed)
    n_nodes = rng.choice([4, 8, 16])
    use_racks = rng.random() < 0.5
    drain_frac = rng.choice([0.0, 0.25, 0.5])

    job = mock.system_job()
    job.task_groups[0].tasks[0].resources.networks = []
    job.task_groups[0].tasks[0].resources.cpu = rng.choice([50, 400])
    if use_racks:
        job.constraints.append(Constraint(
            ltarget="${meta.rack}", operand="=", rtarget="r0"))

    # ONE node list shared by both harnesses (the store copies on
    # upsert): pinned system placement compares node-id SETS, so the
    # clusters must be identical down to the ids.
    nodes = []
    for i in range(n_nodes):
        node = mock.node()
        if use_racks:
            node.meta["rack"] = f"r{i % 2}"
        node.compute_class()
        nodes.append(node)
    drained = [n.id for n in nodes[: int(n_nodes * drain_frac)]]

    h_cpu, h_tpu = Harness(seed=seed), Harness(seed=seed)
    for h in (h_cpu, h_tpu):
        for node in nodes:
            h.state.upsert_node(h.next_index(), node)
        h.state.upsert_job(h.next_index(), job.copy())
        for nid in drained:
            h.state.update_node_drain(h.next_index(), nid, True)

    snap_cpu = h_cpu.state.snapshot()
    snap_tpu = h_tpu.state.snapshot()
    h_cpu.process("system", new_eval(
        h_cpu.state.job_by_id(job.id), consts.EVAL_TRIGGER_NODE_UPDATE))
    h_tpu.process("system-tpu", new_eval(
        h_tpu.state.job_by_id(job.id), consts.EVAL_TRIGGER_NODE_UPDATE))

    cpu_allocs = h_cpu.state.allocs_by_job(job.id)
    tpu_allocs = h_tpu.state.allocs_by_job(job.id)
    assert len(cpu_allocs) == len(tpu_allocs), f"seed {seed}"
    # System placement is pinned: the NODE SETS must match exactly.
    assert ({a.node_id for a in cpu_allocs}
            == {a.node_id for a in tpu_allocs}), f"seed {seed}"
    verify_plan(h_cpu, snap_cpu)
    verify_plan(h_tpu, snap_tpu)


@pytest.mark.parametrize("seed", range(60, 84))
def test_randomized_cpu_tpu_parity(seed):
    seed_state, job = build_scenario(seed)
    host = job.type  # "service" or "batch"
    dense = f"{job.type}-tpu"

    h_cpu, h_tpu = Harness(seed=seed), Harness(seed=seed)
    for h in (h_cpu, h_tpu):
        seed_state(h)
        h.state.upsert_job(h.next_index(), job.copy())
    snap_cpu = h_cpu.state.snapshot()
    snap_tpu = h_tpu.state.snapshot()

    h_cpu.process(host, new_eval(
        h_cpu.state.job_by_id(job.id), consts.EVAL_TRIGGER_JOB_REGISTER))
    h_tpu.process(dense, new_eval(
        h_tpu.state.job_by_id(job.id), consts.EVAL_TRIGGER_JOB_REGISTER))

    cpu_allocs = h_cpu.state.allocs_by_job(job.id)
    tpu_allocs = h_tpu.state.allocs_by_job(job.id)
    assert len(cpu_allocs) == len(tpu_allocs), (
        f"seed {seed}: cpu placed {len(cpu_allocs)}, "
        f"tpu placed {len(tpu_allocs)}")
    assert ({a.name for a in cpu_allocs}
            == {a.name for a in tpu_allocs}), f"seed {seed}"
    cpu_q = h_cpu.evals[0].queued_allocations
    tpu_q = h_tpu.evals[0].queued_allocations
    assert cpu_q == tpu_q, f"seed {seed}: queued {cpu_q} vs {tpu_q}"
    # Same blocked-eval behavior for the remainder.
    assert len(h_cpu.create_evals) == len(h_tpu.create_evals), f"seed {seed}"
    # Both plans pass the applier's per-node verification.
    verify_plan(h_cpu, snap_cpu)
    verify_plan(h_tpu, snap_tpu)


@pytest.mark.parametrize("seed", range(500, 512))
def test_randomized_update_parity(seed):
    """Second eval after a JOB UPDATE (count change, resource bump, or
    constraint tightening): the host and dense factories must agree on
    placement/stop/migrate counts — the reconciler paths (diff_allocs,
    inplace vs destructive update) under the dense backend."""
    rng = random.Random(seed)
    n_nodes = rng.choice([5, 9, 17])
    count0 = rng.choice([3, 6, 10])
    mutation = rng.choice(["grow", "shrink", "resources", "constraint"])

    nodes = []
    for i in range(n_nodes):
        node = mock.node()
        node.meta["rack"] = f"r{i % 3}"
        node.compute_class()
        nodes.append(node)

    job = mock.job()
    job.type = "service"
    tg = job.task_groups[0]
    tg.count = count0
    tg.tasks[0].resources.networks = []
    tg.tasks[0].resources.cpu = 150
    tg.tasks[0].resources.memory_mb = 64

    h_cpu, h_tpu = Harness(seed=seed), Harness(seed=seed)
    for h in (h_cpu, h_tpu):
        for node in nodes:
            h.state.upsert_node(h.next_index(), node)
        h.state.upsert_job(h.next_index(), job.copy())

    h_cpu.process("service", new_eval(
        h_cpu.state.job_by_id(job.id), consts.EVAL_TRIGGER_JOB_REGISTER))
    h_tpu.process("service-tpu", new_eval(
        h_tpu.state.job_by_id(job.id), consts.EVAL_TRIGGER_JOB_REGISTER))
    assert (len(h_cpu.state.allocs_by_job(job.id))
            == len(h_tpu.state.allocs_by_job(job.id))), f"seed {seed} initial"

    updated = job.copy()
    utg = updated.task_groups[0]
    if mutation == "grow":
        utg.count = count0 + rng.choice([2, 5])
    elif mutation == "shrink":
        utg.count = max(1, count0 - 2)
    elif mutation == "resources":
        utg.tasks[0].resources.cpu = 400  # destructive update
    else:
        updated.constraints.append(Constraint(
            ltarget="${meta.rack}", operand="=", rtarget="r0"))
    for h in (h_cpu, h_tpu):
        h.state.upsert_job(h.next_index(), updated.copy())

    h_cpu.process("service", new_eval(
        h_cpu.state.job_by_id(job.id), consts.EVAL_TRIGGER_JOB_REGISTER))
    h_tpu.process("service-tpu", new_eval(
        h_tpu.state.job_by_id(job.id), consts.EVAL_TRIGGER_JOB_REGISTER))

    def live(h):
        return [a for a in h.state.allocs_by_job(job.id)
                if a.desired_status == consts.ALLOC_DESIRED_RUN]

    cpu_live, tpu_live = live(h_cpu), live(h_tpu)
    assert len(cpu_live) == len(tpu_live), (
        f"seed {seed} ({mutation}): cpu {len(cpu_live)} vs "
        f"tpu {len(tpu_live)}")
    if mutation != "constraint":
        # Stops are shape-determined for grow/shrink/resources. For a
        # tightened constraint they depend on WHERE the random initial
        # placements landed, which legitimately differs per harness.
        cpu_stopped = [a for a in h_cpu.state.allocs_by_job(job.id)
                       if a.desired_status == consts.ALLOC_DESIRED_STOP]
        tpu_stopped = [a for a in h_tpu.state.allocs_by_job(job.id)
                       if a.desired_status == consts.ALLOC_DESIRED_STOP]
        assert len(cpu_stopped) == len(tpu_stopped), \
            f"seed {seed} ({mutation})"
    if mutation == "constraint":
        # Every surviving alloc satisfies the tightened constraint —
        # on BOTH factories.
        r0 = {n.id for n in nodes if n.meta["rack"] == "r0"}
        assert all(a.node_id in r0 for a in tpu_live), f"seed {seed}"
        assert all(a.node_id in r0 for a in cpu_live), f"seed {seed}"
