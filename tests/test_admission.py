"""Unit tests for nomad_tpu/admission: token buckets, the admission
controller's level-driven policy, the pressure monitor, the device-path
circuit breaker, deadline derivation, and the new chaos sites."""

import time
from types import SimpleNamespace

import pytest

from nomad_tpu.admission import (
    BREAKER_CLOSED,
    BREAKER_HALF_OPEN,
    BREAKER_OPEN,
    LEVEL_GREEN,
    LEVEL_RED,
    LEVEL_YELLOW,
    ROUTE_EXEMPT,
    ROUTE_READ,
    ROUTE_WRITE,
    RPC_EXEMPT_KINDS,
    AdmissionController,
    AdmissionRejected,
    CircuitBreaker,
    PressureMonitor,
    TokenBucket,
    classify_http,
    deadline_for,
    get_breaker,
    priority_factor,
)
from nomad_tpu.admission import deadline as deadline_mod
from nomad_tpu.server.config import ServerConfig
from nomad_tpu.structs import Evaluation, consts


@pytest.fixture(autouse=True)
def _reset_global_breaker():
    """The breaker is process-global (it guards the one shared device
    path); a tripped state leaked from one test would reroute the
    next test's dense dispatches."""
    yield
    get_breaker().reset()
    get_breaker().configure(failure_threshold=5, slow_ms=0.0,
                            slow_batches=8, cooldown=5.0, enabled=True)


def stub_server(cfg=None, ready=0, unacked=0, blocked=0, shed=0,
                expired=0, in_flight=0, pending=0, max_batch=64,
                max_inflight=2, dispatch_enabled=True,
                ready_by_queue=None):
    cfg = cfg or ServerConfig()
    if ready_by_queue is None:
        # Default: all ready depth on the 'service' queue.
        ready_by_queue = {"service": ready} if ready else {}
    broker = SimpleNamespace(stats=lambda: {
        "ready_by_queue": dict(ready_by_queue),
        "total_ready": ready, "total_unacked": unacked,
        "total_blocked": blocked, "total_waiting": 0,
        "dead_lettered": 0, "shed": shed, "expired": expired,
    })
    dispatch = SimpleNamespace(
        stats=lambda: {
            "enabled": dispatch_enabled, "in_flight": in_flight,
            "pending": pending, "max_batch": max_batch,
        },
        max_inflight=max_inflight,
    )
    return SimpleNamespace(config=cfg, broker=broker, dispatch=dispatch)


# ---------------------------------------------------------------- bucket


def test_token_bucket_burst_then_deficit_hint():
    b = TokenBucket(rate=10.0, burst=2.0)
    ok1, _ = b.try_acquire()
    ok2, _ = b.try_acquire()
    assert ok1 and ok2
    ok3, retry = b.try_acquire()
    assert not ok3
    assert 0.0 < retry <= 0.2  # ~1 token deficit at 10/s
    st = b.stats()
    assert st["granted"] == 2 and st["rejected"] == 1


def test_token_bucket_refills_at_rate():
    b = TokenBucket(rate=100.0, burst=1.0)
    assert b.try_acquire()[0]
    assert not b.try_acquire()[0]
    time.sleep(0.05)  # 100/s refills a full token in 10ms
    assert b.try_acquire()[0]


def test_token_bucket_zero_rate_never_grants_after_burst():
    b = TokenBucket(rate=0.0, burst=1.0)
    assert b.try_acquire()[0]
    ok, retry = b.try_acquire()
    assert not ok and retry > 0


# --------------------------------------------------------------- breaker


def test_breaker_trips_after_k_consecutive_failures_only():
    br = CircuitBreaker(failure_threshold=3, cooldown=60.0)
    br.record_failure()
    br.record_failure()
    br.record_success()  # resets the consecutive count
    br.record_failure()
    br.record_failure()
    assert br.state() == BREAKER_CLOSED
    br.record_failure()
    assert br.state() == BREAKER_OPEN
    assert br.stats()["trips"] == 1
    assert not br.acquire()
    assert br.should_route_host()


def test_breaker_cooldown_half_open_single_probe_then_reclose():
    br = CircuitBreaker(failure_threshold=1, cooldown=0.05)
    br.record_failure()
    assert br.state() == BREAKER_OPEN
    assert not br.acquire()
    time.sleep(0.08)
    # Cool-down elapsed: routing hint goes quiet so traffic reaches
    # the gate, and the FIRST acquire becomes the half-open probe.
    assert not br.should_route_host()
    assert br.acquire()
    assert br.state() == BREAKER_HALF_OPEN
    assert not br.acquire()  # one probe at a time
    br.record_success(duration_ms=1.0)
    assert br.state() == BREAKER_CLOSED
    st = br.stats()
    assert st["half_opens"] == 1 and st["recloses"] == 1
    seq = [(a, b) for (_t, a, b) in br.transitions()]
    assert seq == [
        (BREAKER_CLOSED, BREAKER_OPEN),
        (BREAKER_OPEN, BREAKER_HALF_OPEN),
        (BREAKER_HALF_OPEN, BREAKER_CLOSED),
    ]


def test_breaker_probe_failure_reopens():
    br = CircuitBreaker(failure_threshold=1, cooldown=0.03)
    br.record_failure()
    time.sleep(0.05)
    assert br.acquire()  # probe
    br.record_failure()
    assert br.state() == BREAKER_OPEN
    assert br.stats()["trips"] == 2
    assert not br.acquire()  # cool-down re-armed


def test_breaker_slow_probe_reopens():
    br = CircuitBreaker(failure_threshold=1, cooldown=0.03, slow_ms=10.0)
    br.record_failure()
    time.sleep(0.05)
    assert br.acquire()
    br.record_success(duration_ms=500.0)  # answered, but at 50x budget
    assert br.state() == BREAKER_OPEN


def test_breaker_consecutive_slow_batches_trip():
    br = CircuitBreaker(failure_threshold=99, slow_ms=10.0,
                        slow_batches=2, cooldown=60.0)
    br.record_success(duration_ms=50.0)
    br.record_success(duration_ms=1.0)  # fast success resets
    br.record_success(duration_ms=50.0)
    assert br.state() == BREAKER_CLOSED
    br.record_success(duration_ms=50.0)
    assert br.state() == BREAKER_OPEN


def test_breaker_disabled_is_transparent():
    br = CircuitBreaker(failure_threshold=1, enabled=False)
    br.record_failure()
    br.record_failure()
    assert br.acquire()
    assert br.state() == BREAKER_CLOSED
    assert not br.should_route_host()


# ------------------------------------------------------------- classify


def test_classify_http_route_classes():
    assert classify_http("POST", "/v1/internal/eval/ack") == ROUTE_EXEMPT
    assert classify_http("GET", "/v1/agent/self") == ROUTE_EXEMPT
    assert classify_http("GET", "/v1/metrics") == ROUTE_EXEMPT
    assert classify_http("GET", "/v1/status/leader") == ROUTE_EXEMPT
    # Client control traffic: shedding heartbeats would turn overload
    # into node-down cascades.
    assert classify_http(
        "PUT", "/v1/node/n1/heartbeat", "node_heartbeat") == ROUTE_EXEMPT
    assert classify_http(
        "POST", "/v1/node/n1/allocs", "node_update_allocs") == ROUTE_EXEMPT
    assert classify_http("PUT", "/v1/jobs", "jobs") == ROUTE_WRITE
    assert classify_http("DELETE", "/v1/job/x", "job") == ROUTE_WRITE
    assert classify_http("GET", "/v1/jobs", "jobs") == ROUTE_READ
    assert classify_http("GET", "/v1/allocations") == ROUTE_READ


# ------------------------------------------------------------- pressure


def test_pressure_green_when_quiet():
    mon = PressureMonitor(stub_server(), ServerConfig())
    snap = mon.snapshot(refresh=True)
    assert snap["level"] == LEVEL_GREEN
    assert snap["reasons"] == []


def test_pressure_absolute_depth_thresholds_when_uncapped():
    cfg = ServerConfig(admission_depth_yellow=10, admission_depth_red=20)
    mon = PressureMonitor(stub_server(cfg, ready=8, unacked=3), cfg)
    assert mon.snapshot(refresh=True)["level"] == LEVEL_YELLOW
    mon = PressureMonitor(stub_server(cfg, ready=18, unacked=3), cfg)
    snap = mon.snapshot(refresh=True)
    assert snap["level"] == LEVEL_RED
    assert any("depth" in r for r in snap["reasons"])


def test_pressure_capped_queues_use_cap_fractions():
    cfg = ServerConfig(eval_ready_cap=100)
    # 4 enabled schedulers x 100 = 400 total budget; 300/400 = 75%.
    mon = PressureMonitor(stub_server(cfg, ready=300), cfg)
    assert mon.snapshot(refresh=True)["level"] == LEVEL_YELLOW
    mon = PressureMonitor(stub_server(cfg, ready=395), cfg)
    assert mon.snapshot(refresh=True)["level"] == LEVEL_RED


def test_pressure_uncapped_backlog_is_not_cap_pressure():
    """Backlog on a deliberately-UNCAPPED queue must not read as
    pressure against another queue's cap (it used to: total ready
    across all queues was divided by only the capped budget, so 500
    batch evals drove a false red that shed healthy service traffic).
    It is still visible — through the absolute depth thresholds."""
    cfg = ServerConfig(eval_ready_cap=0, eval_ready_caps={"service": 100})
    mon = PressureMonitor(
        stub_server(cfg, ready=500, ready_by_queue={"batch": 500}), cfg)
    snap = mon.snapshot(refresh=True)
    assert not any("of cap" in r for r in snap["reasons"]), snap
    # Defaults: depth_yellow=256 — the backlog reads as absolute depth.
    assert snap["level"] == LEVEL_YELLOW
    assert any("broker depth" in r for r in snap["reasons"])
    assert snap["inputs"]["ready_capped"] == 0
    # The capped queue itself still drives the fraction.
    mon = PressureMonitor(
        stub_server(cfg, ready=99, ready_by_queue={"service": 99}), cfg)
    snap = mon.snapshot(refresh=True)
    assert snap["level"] == LEVEL_RED
    assert any("of cap" in r for r in snap["reasons"])


def test_pressure_blocked_and_unacked_count_toward_absolute_depth():
    cfg = ServerConfig(admission_depth_yellow=10, admission_depth_red=20)
    mon = PressureMonitor(stub_server(cfg, unacked=6, blocked=6), cfg)
    snap = mon.snapshot(refresh=True)
    assert snap["level"] == LEVEL_YELLOW
    assert snap["inputs"]["blocked"] == 6


def test_pressure_dispatch_saturation():
    cfg = ServerConfig()
    mon = PressureMonitor(
        stub_server(cfg, in_flight=2, pending=64, max_batch=64,
                    max_inflight=2), cfg)
    assert mon.snapshot(refresh=True)["level"] == LEVEL_YELLOW
    mon = PressureMonitor(
        stub_server(cfg, in_flight=2, pending=128, max_batch=64,
                    max_inflight=2), cfg)
    assert mon.snapshot(refresh=True)["level"] == LEVEL_RED


def test_pressure_e2e_p99_input(monkeypatch):
    from nomad_tpu.trace.recorder import FlightRecorder

    monkeypatch.setattr(FlightRecorder, "e2e_p99", lambda self: 900.0)
    cfg = ServerConfig(admission_p99_yellow_ms=500.0,
                       admission_p99_red_ms=2000.0)
    mon = PressureMonitor(stub_server(cfg), cfg)
    snap = mon.snapshot(refresh=True)
    assert snap["level"] == LEVEL_YELLOW
    assert any("p99" in r for r in snap["reasons"])
    assert snap["inputs"]["e2e_p99_ms"] == 900.0


# ------------------------------------------------------------ controller


def make_controller(**cfg_over):
    cfg = ServerConfig(**cfg_over)
    return AdmissionController(stub_server(cfg), cfg)


def test_controller_green_admits_everything():
    ctl = make_controller(admission_write_rate=0.0,
                          admission_write_burst=0.0)
    ctl.check_http("PUT", "/v1/jobs", "jobs")  # no raise even at 0 rate
    ctl.check_rpc("bulk_query")


def test_controller_yellow_rate_limits_writes_429():
    ctl = make_controller(admission_write_rate=100.0,
                          admission_write_burst=1.0)
    ctl.force_level(LEVEL_YELLOW)
    ctl.check_http("PUT", "/v1/jobs", "jobs")  # burst token
    with pytest.raises(AdmissionRejected) as exc:
        ctl.check_http("PUT", "/v1/jobs", "jobs")
    assert exc.value.status == 429
    assert exc.value.retry_after > 0
    # Reads pass under yellow.
    ctl.check_http("GET", "/v1/jobs", "jobs")
    assert ctl.snapshot()["http_rejected"] == 1


def test_controller_red_sheds_writes_503_limits_reads():
    ctl = make_controller(admission_read_rate=100.0,
                          admission_read_burst=1.0,
                          admission_red_retry_after=2.5)
    ctl.force_level(LEVEL_RED)
    with pytest.raises(AdmissionRejected) as exc:
        ctl.check_http("POST", "/v1/jobs", "jobs")
    assert exc.value.status == 503
    assert exc.value.retry_after == 2.5
    ctl.check_http("GET", "/v1/jobs", "jobs")  # read burst token
    with pytest.raises(AdmissionRejected) as exc:
        ctl.check_http("GET", "/v1/jobs", "jobs")
    assert exc.value.status == 429


def test_controller_red_degrades_reads_to_stale_with_replica():
    """With replica state on hand, an over-budget red read degrades to
    the 'stale' verdict (serve local replica) instead of a 429; the
    stub-server path without an fsm keeps the old 429 behavior."""
    cfg = ServerConfig(admission_read_rate=100.0,
                       admission_read_burst=1.0)
    server = stub_server(cfg)
    server.fsm = SimpleNamespace(
        state=SimpleNamespace(latest_index=lambda: 7))
    ctl = AdmissionController(server, cfg)
    ctl.force_level(LEVEL_RED)
    assert ctl.check_http("GET", "/v1/jobs", "jobs") is None  # burst token
    assert ctl.check_http("GET", "/v1/jobs", "jobs") == "stale"
    # No replica yet (index 0) → the 429 path stands.
    server.fsm.state = SimpleNamespace(latest_index=lambda: 0)
    with pytest.raises(AdmissionRejected) as exc:
        ctl.check_http("GET", "/v1/jobs", "jobs")
    assert exc.value.status == 429


def test_controller_exemptions_hold_under_red():
    ctl = make_controller()
    ctl.force_level(LEVEL_RED)
    ctl.check_http("POST", "/v1/internal/plan/submit", "internal_plan_submit")
    ctl.check_http("PUT", "/v1/node/n/heartbeat", "node_heartbeat")
    ctl.check_http("GET", "/v1/metrics", "metrics")
    for kind in sorted(RPC_EXEMPT_KINDS):
        ctl.check_rpc(kind)
    with pytest.raises(AdmissionRejected) as exc:
        ctl.check_rpc("bulk_query")
    assert exc.value.status == 503


def test_controller_disabled_is_transparent():
    ctl = make_controller(admission_enabled=False)
    ctl.force_level(LEVEL_RED)
    ctl.check_http("PUT", "/v1/jobs", "jobs")
    ctl.check_rpc("bulk_query")


# -------------------------------------------------------------- deadline


def test_deadline_priority_scaling():
    assert priority_factor(consts.JOB_DEFAULT_PRIORITY) == 1.0
    assert priority_factor(100) == 1.5
    assert priority_factor(consts.CORE_JOB_PRIORITY) == 2.5
    assert priority_factor(-1000) == 0.25  # floor
    now = 1000.0
    assert deadline_for(50, 30.0, now) == pytest.approx(1030.0)
    assert deadline_for(100, 30.0, now) == pytest.approx(1045.0)
    assert deadline_for(50, 0.0, now) == 0.0  # disabled


def test_deadline_stamp_semantics():
    now = 5000.0
    ev = Evaluation(id="e1", priority=50,
                    status=consts.EVAL_STATUS_PENDING)
    deadline_mod.stamp(ev, 30.0, now)
    assert ev.deadline == pytest.approx(5030.0)
    # Idempotent: a re-commit through the funnel keeps the original.
    deadline_mod.stamp(ev, 99.0, now + 100)
    assert ev.deadline == pytest.approx(5030.0)
    # Terminal evals are never stamped.
    done = Evaluation(id="e2", priority=50,
                      status=consts.EVAL_STATUS_COMPLETE)
    deadline_mod.stamp(done, 30.0, now)
    assert done.deadline == 0.0
    assert not ev.expired(now + 10)
    assert ev.expired(now + 31)


def test_server_eval_update_stamps_fresh_pending_evals():
    from nomad_tpu.server import Server, ServerConfig as SC

    server = Server(SC(num_schedulers=0, eval_deadline_ttl=30.0))
    server.start()
    try:
        ev = Evaluation(id="stamped", priority=50, type="service",
                        job_id="j1", status=consts.EVAL_STATUS_PENDING)
        before = time.time()
        server.eval_update([ev])
        stored = server.fsm.state.eval_by_id("stamped")
        assert stored.deadline == pytest.approx(before + 30.0, abs=2.0)
    finally:
        server.shutdown()


# ------------------------------------------------------------ chaos sites


def test_new_chaos_sites_are_known_and_fire():
    from nomad_tpu.chaos import ChaosInjectedError, FaultSpec, chaos

    schedule = [
        FaultSpec("admission.slow_consumer", "delay", delay=0.0, count=1),
        FaultSpec("device.breaker_trip", "error", count=1),
    ]
    with chaos.armed(11, schedule):
        assert chaos.fire("admission.slow_consumer", eval_id="e") == "delay"
        with pytest.raises(ChaosInjectedError) as exc:
            chaos.fire("device.breaker_trip", eval_id="e")
        assert exc.value.site == "device.breaker_trip"
        log = chaos.firing_log()
    assert {s for s, _n, _k, _d in log} == {
        "admission.slow_consumer", "device.breaker_trip"}


# ----------------------------------------------------- server stats surface


def test_server_stats_expose_admission_surface():
    from nomad_tpu.server import Server, ServerConfig as SC

    server = Server(SC(num_schedulers=0))
    server.start()
    try:
        adm = server.stats()["admission"]
        assert adm["enabled"] is True
        assert adm["pressure"]["level"] == LEVEL_GREEN
        assert "write_bucket" in adm and "read_bucket" in adm
        assert adm["breaker"]["state"] == BREAKER_CLOSED
        broker_stats = server.stats()["broker"]
        assert broker_stats["shed"] == 0 and broker_stats["expired"] == 0
    finally:
        server.shutdown()
