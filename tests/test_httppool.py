"""Keep-alive connection pooling (VERDICT r3 missing #2; reference:
nomad/pool.go:144 ConnPool + rpc.go:137 multiplex): sequential SDK
requests — above all the blocking-query wakeup loop — ride one
persistent socket; socket count scales with CLIENTS, not requests; and
follower workers batch-drain the leader's broker over the pool."""

import threading
import time
from types import SimpleNamespace

import pytest

from nomad_tpu import mock
from nomad_tpu.api import Client, HTTPServer
from nomad_tpu.server import Server, ServerConfig
from nomad_tpu.server.leader_client import RemoteLeader
from nomad_tpu.structs import consts


def wait_until(fn, timeout=5.0, interval=0.02):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if fn():
            return True
        time.sleep(interval)
    return False


@pytest.fixture
def api():
    server = Server(ServerConfig(num_schedulers=1, eval_nack_timeout=5.0))
    server.start()
    http = HTTPServer(server)
    http.start()
    client = Client(http.addr, timeout=10.0)
    yield client, server, http
    http.stop()
    server.shutdown()


def test_sequential_requests_reuse_one_socket(api):
    client, server, http = api
    job = mock.job()
    client.jobs.register(job)
    # A mix of plain queries and short blocking queries: all should
    # ride the single pooled socket.
    _, index = client.jobs.list()
    for _ in range(10):
        client.jobs.list()
        client.jobs.list(index=index, wait=0.05)
        client.nodes.list()
    assert client.pool.dials == 1
    assert http.connections_accepted == 1


def test_puts_and_errors_keep_the_socket(api):
    client, server, http = api
    from nomad_tpu.api.client import APIError

    for i in range(5):
        client.jobs.register(mock.job())
        with pytest.raises(APIError) as e:
            client.jobs.info("no-such-job")
        assert e.value.status == 404
    # Error replies carry Content-Length and must NOT poison reuse.
    assert client.pool.dials == 1
    assert http.connections_accepted == 1


def test_stale_pooled_socket_redials_once(api):
    client, server, http = api
    client.jobs.list()
    assert client.pool.dials == 1
    # Kill the idle socket under the pool (what a server-side idle
    # timeout does between our requests): the next request must
    # transparently retry on a fresh dial.
    import socket as _socket

    with client.pool._lock:
        assert client.pool._idle
        for conn in client.pool._idle:
            # shutdown (not close): the fd stays valid, so checkout
            # hands it out and the REQUEST fails — the keep-alive race
            # shape, exercising the one-retry path.
            conn.sock.shutdown(_socket.SHUT_RDWR)
    jobs, _ = client.jobs.list()
    assert client.pool.dials == 2


def test_longpoll_clients_use_linear_sockets(api):
    """VERDICT r3 #4 acceptance: many long-polling clients, each
    issuing several sequential blocking queries, hold O(clients)
    sockets — not O(requests)."""
    client, server, http = api
    client.jobs.register(mock.job())
    _, index = client.jobs.list()
    before = http.connections_accepted

    n_clients, polls_each = 500, 3
    errors = []

    def poll_loop():
        try:
            c = Client(http.addr, timeout=10.0)
            for _ in range(polls_each):
                c.jobs.list(index=index, wait=0.1)
            assert c.pool.dials == 1
        except Exception as e:  # noqa: BLE001
            errors.append(e)

    threads = [threading.Thread(target=poll_loop) for _ in range(n_clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(30.0)
    assert not errors
    opened = http.connections_accepted - before
    # One socket per client (no retry should trigger here, but allow a
    # whisker of slack for scheduler-dependent keep-alive races).
    assert n_clients <= opened <= n_clients * 1.1, opened


def test_follower_dequeue_many_forwards_to_leader():
    """Follower workers must form device batches too (VERDICT r3 weak
    #4): eval_dequeue_many on a non-leader routes to the leader's
    broker over the internal HTTP route."""
    # A worker-less leader so OUR dequeues are the only consumers.
    server = Server(ServerConfig(num_schedulers=0))
    server.start()
    http = HTTPServer(server)
    http.start()
    try:
        # Park pending evals in the leader's broker (distinct jobs so
        # per-job serialization doesn't hold them back).
        evals = []
        for _ in range(4):
            ev = mock.eval()
            ev.type = consts.JOB_TYPE_SERVICE
            evals.append(ev)
        server.broker.enqueue_all(evals)

        # Direct RemoteLeader exercise (the follower's transport).
        remote = RemoteLeader(http.addr)
        pairs = remote.eval_dequeue_many([consts.JOB_TYPE_SERVICE], 10)
        assert len(pairs) == 4
        for ev, token in pairs:
            assert token
            remote.eval_nack(ev.id, token)  # put them back

        # Full follower path: a server that is NOT the leader and knows
        # the leader only by address resolves it through serf tags and
        # drains over HTTP.
        follower = Server(ServerConfig(num_schedulers=0))
        follower.cluster = {}
        follower.raft = SimpleNamespace(
            leader_id="L", is_leader=lambda: False)
        follower.serf_members = lambda: [SimpleNamespace(
            tags={"rpc_addr": "L", "http_addr": http.addr})]
        assert wait_until(
            lambda: server.broker.stats()["total_ready"] == 4)
        pairs = follower.eval_dequeue_many([consts.JOB_TYPE_SERVICE], 10)
        assert len(pairs) == 4
        for ev, token in pairs:
            server.broker.ack(ev.id, token)
    finally:
        http.stop()
        server.shutdown()


def test_closed_pool_refuses_checkin():
    """A request in flight when close() runs must not park its socket
    into the closed pool's idle list (the SDK swaps pools on address
    change mid-request)."""
    import socket
    import threading

    from nomad_tpu.utils.httppool import HTTPPool

    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    port = srv.getsockname()[1]
    pool = HTTPPool(f"http://127.0.0.1:{port}")

    conn, _pooled = pool._checkout(5.0)
    conn.connect()
    pool.close()
    pool._checkin(conn)
    assert pool._idle == []
    assert conn.sock is None  # closed, not pooled
    srv.close()
