"""Placement-kernel subsystem (nomad_tpu/kernels): registry +
selection surfaces, the convex-relaxation kernel's validity, the
quality scoreboard, and the oracle differential rig — property-style
randomized clusters plus the chaos ride-along (a device fault during a
convex solve must still fall back to the host path)."""

import logging
import random

import numpy as np
import pytest

from nomad_tpu import mock
from nomad_tpu.chaos import FaultSpec, chaos
from nomad_tpu.kernels import (
    active_kernel,
    configure,
    kernel_names,
    kernel_program,
    register_kernel,
)
from nomad_tpu.kernels.differential import (
    DEFAULT_SEEDS,
    build_scenario,
    run_differential,
)
from nomad_tpu.kernels.quality import (
    QualityBoard,
    quality_from_arrays,
    quality_from_store,
    reference_ask,
)
from nomad_tpu.scheduler.testing import Harness, seed_harness_cluster
from nomad_tpu.structs import consts, new_eval


@pytest.fixture(autouse=True)
def _restore_kernel():
    before = active_kernel()
    yield
    configure(before)
    chaos.disarm()


# --------------------------------------------------------------- registry


def test_registry_builtins_and_unknown():
    assert {"greedy", "convex"} <= set(kernel_names())
    with pytest.raises(ValueError, match="unknown placement kernel"):
        configure("cvx")
    configure("convex")
    assert active_kernel() == "convex"
    assert callable(kernel_program("convex"))
    with pytest.raises(ValueError, match="unknown placement kernel"):
        kernel_program("nope")


def test_registry_rejects_dashed_names():
    # Kernel names embed into factory names ("service-<k>-tpu");
    # dashes would make the host_factory strip-back ambiguous.
    with pytest.raises(ValueError, match="no dashes"):
        register_kernel("my-kernel", lambda: None)


def test_registry_rejects_replacing_greedy():
    # placement_program runs the native scan for "greedy" without
    # consulting the registry; a replacement loader would silently
    # never run.
    with pytest.raises(ValueError, match="cannot be replaced"):
        register_kernel("greedy", lambda: None)


def test_greedy_resolves_through_registry():
    from nomad_tpu.ops.binpack import placement_program

    assert kernel_program("greedy") is placement_program


def test_second_default_server_does_not_reset_active_kernel():
    """Process-global semantics: constructing a default-configured
    Server must not flip an explicitly configured kernel back."""
    from nomad_tpu.server import Server, ServerConfig

    configure("convex")
    server = Server(ServerConfig(num_schedulers=1))
    try:
        assert active_kernel() == "convex"
    finally:
        server.shutdown()


def test_third_party_kernel_registers_and_routes():
    """A plugin kernel becomes selectable through configure() and the
    factory registry; its loader resolves lazily on first dispatch."""
    from nomad_tpu.kernels.convex import convex_placement_program

    loads = []

    def loader():
        loads.append(1)
        return convex_placement_program

    register_kernel("thirdparty", loader)
    try:
        assert "thirdparty" in kernel_names()
        assert not loads  # lazy: registration must not load
        configure("thirdparty")
        assert kernel_program("thirdparty") is convex_placement_program
        assert loads == [1]
        kernel_program("thirdparty")
        assert loads == [1]  # memoized

        # The factory seam picks it up (fresh lazy registration).
        from nomad_tpu import scheduler as sched_mod

        for name in [n for n in sched_mod.scheduler_names()
                     if n.endswith("-tpu")]:
            sched_mod._BUILTIN.pop(name)
        h = Harness()
        s = sched_mod.new_scheduler(
            "service-thirdparty-tpu", logging.getLogger("t"),
            h.snapshot(), h)
        assert s.kernel == "thirdparty"
    finally:
        from nomad_tpu import kernels as kmod
        from nomad_tpu import scheduler as sched_mod

        with kmod._LOCK:
            kmod._LOADERS.pop("thirdparty", None)
            kmod._PROGRAMS.pop("thirdparty", None)
            kmod._NAMES = tuple(sorted(kmod._LOADERS))
        # Also drop the lazily-registered factory variants: a later
        # test resolving service-thirdparty-tpu would otherwise get a
        # scheduler pinned to a kernel that no longer exists.
        for name in [n for n in sched_mod.scheduler_names()
                     if "-thirdparty-" in n]:
            sched_mod._BUILTIN.pop(name)


# ---------------------------------------------------------------- quality


def test_quality_from_arrays_known_cases():
    capacity = np.array([[100, 100, 0, 0]] * 4, float)
    node_ok = np.array([True, True, True, False])
    ask = np.array([40, 40, 0, 0], float)
    # Node 0 full (strands nothing: no free), node 1 at 80 (free 20 —
    # cannot fit 40: stranded), node 2 empty (free fits: not
    # stranded), node 3 down (ignored).
    util = np.array([[100, 100, 0, 0], [80, 80, 0, 0],
                     [0, 0, 0, 0], [0, 0, 0, 0]], float)
    q = quality_from_arrays(util, capacity, node_ok, ask)
    # Free weight: node0 0, node1 0.4, node2 2.0 -> stranded 0.4/2.4.
    assert q["fragmentation"] == pytest.approx(0.4 / 2.4)
    # Occupied nodes 0 and 1: mean(max fill) = (1.0 + 0.8) / 2.
    assert q["binpack_score"] == pytest.approx(0.9)

    empty = quality_from_arrays(
        np.zeros((2, 4)), np.zeros((2, 4)), np.zeros(2, bool), ask)
    assert empty == {"fragmentation": 0.0, "binpack_score": 0.0}


def test_quality_board_rings_and_snapshot():
    board = QualityBoard()
    for i in range(600):  # wraps the 512-cap ring
        board.note_plan("greedy", 0.25, 0.5)
    board.note_plan("convex", 0.1, 0.8)
    snap = board.snapshot()
    assert snap["kernels"]["greedy"]["samples"] == 600
    assert snap["kernels"]["greedy"]["fragmentation"] == 0.25
    assert snap["kernels"]["convex"]["binpack_score"] == 0.8
    assert "queueing_delay_ms" in snap
    board.reset()
    assert board.snapshot()["kernels"] == {}


def test_quality_from_store_matches_cluster_state():
    h = Harness()
    nodes = [mock.node() for _ in range(4)]
    for n in nodes:
        n.compute_class()
    job = mock.job()
    seed_harness_cluster(h, nodes=nodes, jobs=[job])
    q = quality_from_store(h.state.snapshot(), job)
    assert set(q) == {"fragmentation", "binpack_score"}
    assert reference_ask(job)[0] > 0


# ------------------------------------------------- differential property


@pytest.mark.parametrize("seed", list(DEFAULT_SEEDS)[:8])
def test_convex_kernel_oracle_differential(seed):
    """Property-style: on randomized clusters (mixed resources,
    distinct-hosts, drained nodes, pre-load) every placement the
    convex kernel emits is oracle-feasible, capacity-safe, and
    plan-apply-accepted."""
    report = run_differential("convex", seeds=[seed])
    assert report["green"], "\n".join(report["violations"])


def test_greedy_kernel_oracle_differential_sample():
    report = run_differential("greedy", seeds=list(DEFAULT_SEEDS)[:3])
    assert report["green"], "\n".join(report["violations"])


def test_differential_rig_catches_a_lying_kernel():
    """The rig must be able to FAIL: a kernel that places on drained /
    infeasible nodes (bypassing the feasibility mask) produces
    violations — a rig that can't go red proves nothing."""
    from nomad_tpu.kernels import _LOCK, _LOADERS, _PROGRAMS
    from nomad_tpu.ops import binpack as bp

    def cheating_program(state, asks, key, config):
        import jax.numpy as jnp

        # Always "place" every ask on row 0 regardless of feasibility.
        k = asks.resources.shape[0]
        choices = jnp.zeros(k, jnp.int32)
        scores = jnp.zeros(k, jnp.float32)
        return choices, scores, state

    register_kernel("cheat", lambda: cheating_program)
    try:
        # A seed whose scenario has drained nodes/pre-load so row 0 is
        # wrong somewhere across the sweep.
        report = run_differential("cheat", seeds=list(DEFAULT_SEEDS)[:4])
        assert not report["green"]
        assert report["violations"]
    finally:
        with _LOCK:
            _LOADERS.pop("cheat", None)
            _PROGRAMS.pop("cheat", None)


# ------------------------------------------------------ chaos ride-along


def test_breaker_trip_during_convex_solve_falls_back_to_host():
    """device.breaker_trip fires while the convex kernel is selected:
    the dense scheduler's device-fault fallback must complete the eval
    on the host path with a full, valid placement set."""
    seed_state, job = build_scenario(7100)
    # Force a deterministic, feasible-ish shape: service, no distinct
    # surprises needed — the point is the fallback, the rig covers
    # validity.
    h = Harness(seed=11)
    seed_state(h, job)
    chaos.arm(11, [FaultSpec("device.breaker_trip", "error", count=1)])
    try:
        h.process(f"{job.type}-convex-tpu", new_eval(
            h.state.job_by_id(job.id), consts.EVAL_TRIGGER_JOB_REGISTER))
        fired = chaos.firing_log()  # (site, ordinal, kind, delay)
        assert any(site == "device.breaker_trip"
                   for (site, _seq, _kind, _d) in fired), fired
    finally:
        chaos.disarm()
    assert h.evals and h.evals[-1].status == consts.EVAL_STATUS_COMPLETE
    # The host fallback still placed (same count a clean convex run
    # yields on this seed).
    clean = Harness(seed=11)
    seed_state(clean, job)
    clean.process(f"{job.type}-convex-tpu", new_eval(
        clean.state.job_by_id(job.id), consts.EVAL_TRIGGER_JOB_REGISTER))
    placed_chaos = len(h.state.allocs_by_job(job.id))
    placed_clean = len(clean.state.allocs_by_job(job.id))
    assert placed_chaos == placed_clean and placed_chaos > 0


# ---------------------------------------------------- kernel unit checks


def test_convex_program_respects_padding_and_feasibility():
    """Direct kernel-program check on a hand-built state: inactive
    (padding) asks yield -1, placements never land on not-ok nodes,
    and the carried capacity is honored."""
    import jax.numpy as jnp

    from nomad_tpu.ops.binpack import (
        PlacementConfig,
        host_prng_key,
        make_asks,
        make_node_state,
        placement_program_jit,
    )

    n, g, k = 8, 1, 4
    capacity = np.full((n, 4), 100.0)
    state = make_node_state(
        capacity=capacity, sched_capacity=capacity,
        util=np.zeros((n, 4)), bw_avail=np.full(n, 1000.0),
        bw_used=np.zeros(n), ports_free=np.full(n, 100.0),
        job_count=np.zeros(n, np.int32),
        tg_count=np.zeros((n, g), np.int32),
        feasible=np.concatenate(
            [np.ones((4, g), bool), np.zeros((4, g), bool)]),
        node_ok=np.array([True, True, True, False,
                          True, True, True, True]),
    )
    # 3 active asks of 60 each: at most one fits per node (100 cap),
    # only rows 0-2 are feasible AND ok.
    asks = make_asks(
        resources=np.array([[60, 60, 0, 0]] * k, np.float32),
        bw=np.zeros(k), ports=np.zeros(k),
        tg_index=np.zeros(k, np.int32),
        active=np.array([True, True, True, False]),
        job_distinct_hosts=False, tg_distinct_hosts=np.zeros(g, bool),
    )
    config = PlacementConfig(anti_affinity_penalty=10.0, kernel="convex")
    choices, scores, final = placement_program_jit(
        state, asks, host_prng_key(5), config)
    choices = np.asarray(choices)
    assert choices[3] == -1  # padding row
    placed = choices[:3]
    assert set(placed.tolist()) <= {0, 1, 2}
    assert len(set(placed.tolist())) == 3  # 60+60 > 100: one per node
    final_util = np.asarray(final.util)
    assert (final_util <= capacity + 1e-6).all()
