"""Incremental cluster-base updates (models/matrix.py _BASE_FAMILY +
_ClusterBase.delta_update): a snapshot that only advanced the allocs
table recomputes touched node rows instead of a full O(N x allocs)
rebuild, and the delta result must be bit-identical to a fresh build —
the live pipeline's per-apply snapshot churn rides this path."""

import numpy as np
import pytest

from nomad_tpu import mock
from nomad_tpu.models.matrix import ClusterMatrix, _ClusterBase
from nomad_tpu.state import StateStore
from nomad_tpu.structs import consts


def make_alloc(node, job, cpu=100, mem=128):
    alloc = mock.alloc()
    alloc.node_id = node.id
    alloc.job_id = job.id
    alloc.job = job
    alloc.desired_status = consts.ALLOC_DESIRED_RUN
    alloc.client_status = consts.ALLOC_CLIENT_RUNNING
    for tr in alloc.task_resources.values():
        tr.cpu = cpu
        tr.memory_mb = mem
        tr.networks = []
    alloc.resources = None
    return alloc


@pytest.fixture
def cluster():
    store = StateStore()
    job = mock.job()
    job.task_groups[0].tasks[0].resources.networks = []
    nodes = []
    index = 0
    for _ in range(16):
        node = mock.node()
        node.compute_class()
        nodes.append(node)
        index += 1
        store.upsert_node(index, node)
    allocs = [make_alloc(nodes[i % 16], job) for i in range(32)]
    index += 1
    store.upsert_allocs(index, allocs)
    return store, job, nodes, allocs, index


def assert_bases_equal(a, b):
    for f in ("capacity", "sched_capacity", "util", "bw_avail",
              "bw_used", "ports_free", "node_ok"):
        np.testing.assert_array_equal(
            getattr(a, f), getattr(b, f), err_msg=f)
    assert a.alloc_groups == b.alloc_groups


def test_delta_update_matches_full_rebuild(cluster):
    store, job, nodes, allocs, index = cluster
    m1 = ClusterMatrix(store.snapshot(), job)
    tok1 = m1.base_token

    # Stop some allocs and add new ones: the allocs index advances.
    stopped = allocs[:5]
    for a in stopped:
        a.desired_status = consts.ALLOC_DESIRED_STOP
        a.client_status = consts.ALLOC_CLIENT_COMPLETE
    index += 1
    store.upsert_allocs(index, stopped)
    fresh = [make_alloc(nodes[3], job, cpu=250), make_alloc(nodes[7], job)]
    index += 1
    store.upsert_allocs(index, fresh)

    snap = store.snapshot()
    m2 = ClusterMatrix(snap, job)  # delta path (family hit)
    assert m2.base_token != tok1
    # Oracle: a from-scratch base on the same snapshot.
    oracle = _ClusterBase(
        m2.nodes,
        lambda nid: snap.allocs_by_node_terminal(nid, False))
    assert_bases_equal(m2._cached_base(), oracle)


def test_unchanged_allocs_reuse_token(cluster):
    """An allocs-index bump that touches no node in this matrix's node
    set keeps the SAME base token — the device-cached upload stays
    valid with zero new transfers."""
    store, job, nodes, allocs, index = cluster
    m1 = ClusterMatrix(store.snapshot(), job)
    tok1 = m1.base_token
    # Touch an alloc on a node in another datacenter (outside this
    # job's node set).
    other = mock.node()
    other.datacenter = "dc-elsewhere"
    other.compute_class()
    index += 1
    store.upsert_node(index, other)
    m_after_node = ClusterMatrix(store.snapshot(), job)
    # nodes index moved: new family -> full rebuild is expected here.
    far_job = mock.job()
    far_job.id = "far"
    index += 1
    store.upsert_allocs(index, [make_alloc(other, far_job)])
    m2 = ClusterMatrix(store.snapshot(), job)
    assert m2.base_token == m_after_node.base_token


def test_many_changed_rows_falls_back_to_full_rebuild(cluster):
    store, job, nodes, allocs, index = cluster
    ClusterMatrix(store.snapshot(), job)
    # Touch every node (> n/4 rows): delta declines, full rebuild runs.
    for a in allocs:
        a.client_status = consts.ALLOC_CLIENT_COMPLETE
        a.desired_status = consts.ALLOC_DESIRED_STOP
    index += 1
    store.upsert_allocs(index, allocs)
    snap = store.snapshot()
    m2 = ClusterMatrix(snap, job)
    oracle = _ClusterBase(
        m2.nodes, lambda nid: snap.allocs_by_node_terminal(nid, False))
    assert_bases_equal(m2._cached_base(), oracle)
    # All allocs stopped: utilization back to reserved-only.
    assert float(m2.util[: m2.n_real, 0].max()) <= max(
        (n.reserved.cpu if n.reserved else 0) for n in m2.nodes)


def test_delta_patches_positions_index(cluster):
    """A delta base carries the parent's job-positions index forward,
    patching only jobs in the changed rows; the result must equal a
    from-scratch index (same multiset of rows per job/task-group)."""
    store, job, nodes, allocs, index = cluster
    m1 = ClusterMatrix(store.snapshot(), job)
    parent = m1._cached_base()
    parent.job_positions(job.id)  # force the parent index to exist

    stopped = allocs[:3]
    for a in stopped:
        a.desired_status = consts.ALLOC_DESIRED_STOP
        a.client_status = consts.ALLOC_CLIENT_COMPLETE
    index += 1
    store.upsert_allocs(index, stopped)
    other = mock.job()
    other.id = "other-job"
    index += 1
    store.upsert_allocs(index, [make_alloc(nodes[5], other)])

    snap = store.snapshot()
    m2 = ClusterMatrix(snap, job)
    base2 = m2._cached_base()
    assert base2.delta_parent is not None  # took the delta path
    # Patched index was installed without a lazy rebuild.
    assert base2._positions is not None
    oracle = _ClusterBase(
        m2.nodes, lambda nid: snap.allocs_by_node_terminal(nid, False))
    for jid in (job.id, other.id, "no-such-job"):
        got = {tg: sorted(arr.tolist())
               for tg, arr in base2.job_positions(jid).items()}
        want = {tg: sorted(arr.tolist())
                for tg, arr in oracle.job_positions(jid).items()}
        assert got == want, jid


def test_gc_deletion_forces_full_rebuild(cluster):
    """Deleted allocs leave no modify_index trace; the delta path must
    detect the shrinking table and rebuild, or the deleted usage stays
    baked into the base forever (GC via delete_evals pops allocs)."""
    store, job, nodes, allocs, index = cluster
    m1 = ClusterMatrix(store.snapshot(), job)
    util_before = m1.util[: m1.n_real].sum()
    victims = allocs[:4]
    index += 1
    store.delete_evals(index, [], [a.id for a in victims])
    snap = store.snapshot()
    m2 = ClusterMatrix(snap, job)
    oracle = _ClusterBase(
        m2.nodes, lambda nid: snap.allocs_by_node_terminal(nid, False))
    assert_bases_equal(m2._cached_base(), oracle)
    assert m2.util[: m2.n_real].sum() < util_before


def test_explicit_node_subsets_do_not_collide(cluster):
    """Two equal-sized but different pinned-node subsets on one
    snapshot (the dense system scheduler's shape) must get distinct
    bases — round-3 bug: the cache keyed node identity by len()."""
    store, job, nodes, allocs, index = cluster
    snap = store.snapshot()
    sub_a, sub_b = nodes[:4], nodes[4:8]
    ma = ClusterMatrix(snap, job, nodes=sub_a)
    mb = ClusterMatrix(snap, job, nodes=sub_b)
    assert ma.base_token != mb.base_token
    for m, subset in ((ma, sub_a), (mb, sub_b)):
        oracle = _ClusterBase(
            subset, lambda nid: snap.allocs_by_node_terminal(nid, False))
        assert_bases_equal(m._cached_base(), oracle)
    # Same subset again: cache hit, same token.
    ma2 = ClusterMatrix(snap, job, nodes=sub_a)
    assert ma2.base_token == ma.base_token


def test_off_set_creations_keep_delta_path_alive(cluster):
    """Alloc creations OUTSIDE the family's node set must rekey without
    poisoning table_len — a stale length tripped the deletion check and
    degraded every later delta to a full rebuild."""
    store, job, nodes, allocs, index = cluster
    far = mock.node()
    far.datacenter = "dc-elsewhere"
    far.compute_class()
    index += 1
    store.upsert_node(index, far)
    far_job = mock.job()
    far_job.id = "far"
    m = ClusterMatrix(store.snapshot(), job)
    token = m.base_token
    for step in range(5):
        # Creation on the out-of-set node: rekey (token unchanged) ...
        index += 1
        store.upsert_allocs(index, [make_alloc(far, far_job)])
        m = ClusterMatrix(store.snapshot(), job)
        assert m.base_token == token, f"rekey broke at step {step}"
    # ... and an in-set change afterwards still takes the DELTA path
    # (correct base, new token) rather than a full rebuild with drift.
    index += 1
    store.upsert_allocs(index, [make_alloc(nodes[2], job, cpu=75)])
    snap = store.snapshot()
    m2 = ClusterMatrix(snap, job)
    assert m2.base_token != token
    oracle = _ClusterBase(
        m2.nodes, lambda nid: snap.allocs_by_node_terminal(nid, False))
    assert_bases_equal(m2._cached_base(), oracle)


def test_chained_deltas_stay_correct(cluster):
    """Repeated small changes (the live pipeline's per-apply churn)
    accumulate through chained delta updates without drift."""
    store, job, nodes, allocs, index = cluster
    rng_nodes = nodes
    for step in range(6):
        ClusterMatrix(store.snapshot(), job)
        index += 1
        store.upsert_allocs(index, [
            make_alloc(rng_nodes[(step * 3) % 16], job, cpu=50 + step)])
    snap = store.snapshot()
    m = ClusterMatrix(snap, job)
    oracle = _ClusterBase(
        m.nodes, lambda nid: snap.allocs_by_node_terminal(nid, False))
    assert_bases_equal(m._cached_base(), oracle)


def test_additive_delta_for_pure_creations(cluster):
    """A placement storm is pure CREATIONS: even when they touch most
    nodes (past the refill cap), the delta path must survive by
    scatter-adding the new allocs' usage — the quadratic-storm fix —
    and stay bit-identical to a fresh build."""
    store, job, nodes, allocs, index = cluster
    m1 = ClusterMatrix(store.snapshot(), job)
    tok1 = m1.base_token

    # New allocs on EVERY node (16 rows > the 16//4 refill cap, and
    # far over it proportionally at scale).
    fresh = [make_alloc(n, job, cpu=30 + i) for i, n in enumerate(nodes)]
    index += 1
    store.upsert_allocs(index, fresh)
    snap = store.snapshot()
    m2 = ClusterMatrix(snap, job)
    base = m2._cached_base()
    # Delta, not rebuild: the chain to the parent is recorded.
    assert m2.base_token != tok1
    assert base.delta_parent is not None and base.delta_parent[0] == tok1
    oracle = _ClusterBase(
        m2.nodes, lambda nid: snap.allocs_by_node_terminal(nid, False))
    assert_bases_equal(base, oracle)


def test_additive_delta_skips_created_then_terminal(cluster):
    """An alloc created AND terminated since the base was built never
    consumed capacity the base saw: it must contribute nothing."""
    store, job, nodes, allocs, index = cluster
    m1 = ClusterMatrix(store.snapshot(), job)
    tok1 = m1.base_token

    ghost = make_alloc(nodes[4], job, cpu=999)
    ghost.client_status = consts.ALLOC_CLIENT_COMPLETE
    live = make_alloc(nodes[9], job, cpu=40)
    index += 1
    store.upsert_allocs(index, [ghost, live])
    snap = store.snapshot()
    m2 = ClusterMatrix(snap, job)
    oracle = _ClusterBase(
        m2.nodes, lambda nid: snap.allocs_by_node_terminal(nid, False))
    assert_bases_equal(m2._cached_base(), oracle)
    assert m2.base_token != tok1


def test_mixed_creations_and_modifications(cluster):
    """Creations on some nodes + a terminal transition on another in
    ONE index step: the modified node refills, the created ones
    scatter-add, and the result matches a fresh build."""
    store, job, nodes, allocs, index = cluster
    ClusterMatrix(store.snapshot(), job)

    stopped = allocs[0]
    stopped.desired_status = consts.ALLOC_DESIRED_STOP
    stopped.client_status = consts.ALLOC_CLIENT_COMPLETE
    fresh = [make_alloc(nodes[i], job, cpu=20) for i in (2, 5, 11)]
    index += 1
    store.upsert_allocs(index, [stopped] + fresh)
    snap = store.snapshot()
    m2 = ClusterMatrix(snap, job)
    oracle = _ClusterBase(
        m2.nodes, lambda nid: snap.allocs_by_node_terminal(nid, False))
    assert_bases_equal(m2._cached_base(), oracle)


def test_addition_on_refilled_node_not_double_counted(cluster):
    """A creation landing on the SAME node as a modification must ride
    the refill (which already reads current allocs), not also
    scatter-add — double-counting would inflate utilization and cause
    phantom capacity exhaustion."""
    store, job, nodes, allocs, index = cluster
    ClusterMatrix(store.snapshot(), job)

    target = nodes[6]
    stopped = next(a for a in allocs if a.node_id == target.id)
    stopped.desired_status = consts.ALLOC_DESIRED_STOP
    stopped.client_status = consts.ALLOC_CLIENT_COMPLETE
    fresh = make_alloc(target, job, cpu=70)
    index += 1
    store.upsert_allocs(index, [stopped, fresh])
    snap = store.snapshot()
    m2 = ClusterMatrix(snap, job)
    oracle = _ClusterBase(
        m2.nodes, lambda nid: snap.allocs_by_node_terminal(nid, False))
    assert_bases_equal(m2._cached_base(), oracle)
