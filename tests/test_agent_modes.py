"""External-binary agent tests: config-file-driven server-only and
client-only agents wired into one cluster (mirror testutil/server.go's
exec-a-real-binary harness and agent.go's server/client composition)."""

import json
import os
import subprocess
import sys
import time
import urllib.error
import urllib.request

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def wait_http(url, timeout=20.0):
    deadline = time.monotonic() + timeout
    last = None
    while time.monotonic() < deadline:
        try:
            with urllib.request.urlopen(url, timeout=2.0) as resp:
                return json.loads(resp.read())
        except Exception as e:  # noqa: BLE001
            last = e
            time.sleep(0.3)
    raise AssertionError(f"{url} never became ready: {last}")


def spawn_agent(config_path, *extra):
    proc = subprocess.Popen(
        [sys.executable, "-m", "nomad_tpu.cli", "agent",
         "-config", str(config_path), *extra],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env={**os.environ, "PYTHONPATH": os.pathsep.join(
            p for p in [REPO, os.environ.get("PYTHONPATH", "")] if p)},
    )
    return proc


@pytest.fixture
def server_client_cluster(tmp_path):
    """A server-only agent and a client-only agent from config files."""
    server_cfg = tmp_path / "server.hcl"
    server_cfg.write_text("""
        bind_addr = "127.0.0.1"
        ports { http = 14846 }
        server {
          enabled        = true
          num_schedulers = 1
        }
    """)
    client_cfg = tmp_path / "client.json"
    client_cfg.write_text(json.dumps({
        "bind_addr": "127.0.0.1",
        "ports": {"http": 14847},
        "client": {
            "enabled": True,
            "servers": ["127.0.0.1:14846"],
            "state_dir": str(tmp_path / "state"),
            "alloc_dir": str(tmp_path / "alloc"),
            "node_class": "cfg-test",
            "meta": {"origin": "configfile"},
            "options": {"driver.raw_exec.enable": "1"},
        },
    }))
    server = spawn_agent(server_cfg)
    try:
        wait_http("http://127.0.0.1:14846/v1/status/leader")
        client = spawn_agent(client_cfg)
        try:
            yield server, client
        finally:
            client.terminate()
            client.wait(timeout=10)
    finally:
        server.terminate()
        server.wait(timeout=10)


def test_server_only_and_client_only_agents(server_client_cluster, tmp_path):
    server, client = server_client_cluster
    # The client registers against the server-only agent with the
    # attributes from its config file.
    deadline = time.monotonic() + 20
    nodes = []
    while time.monotonic() < deadline:
        nodes = wait_http("http://127.0.0.1:14846/v1/nodes")
        if nodes and nodes[0].get("status") == "ready":
            break
        time.sleep(0.3)
    assert nodes, "client never registered"
    assert nodes[0]["node_class"] == "cfg-test"

    node = wait_http(f"http://127.0.0.1:14846/v1/node/{nodes[0]['id']}")
    assert node["meta"]["origin"] == "configfile"

    # A job submitted to the server runs on the client-only agent.
    jobfile = tmp_path / "job.hcl"
    jobfile.write_text("""
        job "cfgjob" {
          datacenters = ["dc1"]
          type = "batch"
          group "g" {
            restart { attempts = 0  mode = "fail" }
            task "t" {
              driver = "raw_exec"
              config { command = "/bin/sh"  args = ["-c", "exit 0"] }
              resources { cpu = 50  memory = 32 }
            }
          }
        }
    """)
    out = subprocess.run(
        [sys.executable, "-m", "nomad_tpu.cli",
         "--address", "http://127.0.0.1:14846", "run", "-detach",
         str(jobfile)],
        capture_output=True, text=True,
        env={**os.environ, "PYTHONPATH": os.pathsep.join(
            p for p in [REPO, os.environ.get("PYTHONPATH", "")] if p)}, timeout=60)
    assert out.returncode == 0, out.stdout + out.stderr

    deadline = time.monotonic() + 30
    final = None
    while time.monotonic() < deadline:
        allocs = wait_http(
            "http://127.0.0.1:14846/v1/job/cfgjob/allocations")
        if allocs and allocs[0]["client_status"] == "complete":
            final = allocs[0]
            break
        time.sleep(0.3)
    assert final is not None, "batch job never completed on client agent"

    # The client-only agent serves its own HTTP endpoint: fs/logs for
    # its allocations are reachable there (every agent serves HTTP,
    # agent.go), while server-backed routes answer 501.
    listing = wait_http(
        f"http://127.0.0.1:14847/v1/client/fs/ls/{final['id']}")
    assert any(e["name"] == "alloc" for e in listing)
    servers = wait_http("http://127.0.0.1:14847/v1/agent/servers")
    assert servers == ["http://127.0.0.1:14846"]
    try:
        urllib.request.urlopen("http://127.0.0.1:14847/v1/jobs", timeout=5)
        raise AssertionError("server route should 501 on client-only agent")
    except urllib.error.HTTPError as e:
        assert e.code == 501


def test_agent_requires_role(tmp_path):
    """An agent with neither server nor client enabled refuses to start."""
    cfg = tmp_path / "empty.hcl"
    cfg.write_text('region = "eu"\n')
    proc = spawn_agent(cfg)
    out, _ = proc.communicate(timeout=30)
    assert proc.returncode == 1
    assert "must have server, client, or both" in out


def test_agent_bad_config_errors(tmp_path):
    cfg = tmp_path / "bad.hcl"
    cfg.write_text('nonsense_key = true\n')
    proc = spawn_agent(cfg)
    out, _ = proc.communicate(timeout=30)
    assert proc.returncode == 1
    assert "unknown config keys" in out


def test_three_server_raft_cluster_from_configs(tmp_path):
    """bootstrap_expect=3: three config-file agents discover each other
    through gossip, form a raft cluster, elect ONE leader, and a job
    submitted to a FOLLOWER schedules through log forwarding."""
    ports = [15851, 15852, 15853]
    serf_seed = f"127.0.0.1:{ports[0] + 100}"
    procs = []
    for i, port in enumerate(ports):
        cfg = tmp_path / f"s{i}.hcl"
        join = f'retry_join = ["{serf_seed}"]' if i else ""
        cfg.write_text(f"""
            bind_addr = "127.0.0.1"
            name = "raft-s{i}"
            data_dir = "{tmp_path}/data{i}"
            ports {{ http = {port}  rpc = {port + 50}  serf = {port + 100} }}
            server {{
              enabled          = true
              bootstrap_expect = 3
              num_schedulers   = 1
              {join}
            }}
            client {{
              enabled = true
              options {{ "driver.raw_exec.enable" = "1" }}
            }}
        """)
        procs.append(spawn_agent(cfg))
        if i == 0:
            # seed first: the others' first retry_join attempt then
            # lands instead of waiting out a full retry interval
            wait_http(f"http://127.0.0.1:{port}/v1/agent/members",
                      timeout=30)
    try:
        # gossip convergence: every agent sees all three members
        deadline = time.monotonic() + 40
        while time.monotonic() < deadline:
            try:
                members = wait_http(
                    f"http://127.0.0.1:{ports[0]}/v1/agent/members",
                    timeout=5)
                if len(members) == 3:
                    break
            except AssertionError:
                pass
            time.sleep(0.5)

        # exactly one leader across the cluster
        def leaders():
            out = []
            for port in ports:
                try:
                    led = wait_http(
                        f"http://127.0.0.1:{port}/v1/status/leader",
                        timeout=5)
                    out.append(led)
                except AssertionError:
                    out.append("")
            return out

        deadline = time.monotonic() + 40
        led = []
        while time.monotonic() < deadline:
            led = leaders()
            nonempty = [x for x in led if x]
            if len(nonempty) == 3 and len(set(nonempty)) == 1 and nonempty[0]:
                break
            time.sleep(0.5)
        nonempty = [x for x in led if x]
        assert len(set(nonempty)) == 1 and len(nonempty) == 3, led

        # submit a zero-count job to a follower: the write forwards to
        # the leader through the raft log and lands everywhere
        leader_url = nonempty[0]
        follower_port = next(
            p for p in ports if f":{p}" not in leader_url)
        job = {"job": {"id": "raftjob", "name": "raftjob",
                       "type": "service", "datacenters": ["dc1"],
                       "task_groups": [{"name": "g", "count": 0,
                                        "tasks": [{"name": "t",
                                                   "driver": "mock_driver",
                                                   "resources": {"cpu": 10,
                                                                 "memory_mb": 8}}]}]}}
        import urllib.request as _ur
        req = _ur.Request(f"http://127.0.0.1:{follower_port}/v1/jobs",
                          data=json.dumps(job).encode(), method="PUT",
                          headers={"Content-Type": "application/json"})
        _ur.urlopen(req, timeout=15)
        for port in ports:
            deadline = time.monotonic() + 15
            found = False
            while time.monotonic() < deadline and not found:
                try:
                    got = wait_http(
                        f"http://127.0.0.1:{port}/v1/job/raftjob", timeout=5)
                    found = got.get("id") == "raftjob"
                except AssertionError:
                    pass
                time.sleep(0.3)
            assert found, f"job not replicated to server on port {port}"

        # A REAL workload completes: clients are co-located with every
        # server (2 of 3 heartbeat through followers -> remote leader
        # forwarding), and whichever server's worker dequeues the eval
        # reaches the leader's broker the same way (rpc.go:178).
        batch = {"job": {"id": "raftbatch", "name": "raftbatch",
                         "type": "batch", "datacenters": ["dc1"],
                         "task_groups": [{
                             "name": "g", "count": 1,
                             "restart_policy": {"attempts": 0,
                                                "mode": "fail"},
                             "tasks": [{"name": "t", "driver": "raw_exec",
                                        "config": {"command": "/bin/sh",
                                                   "args": ["-c", "exit 0"]},
                                        "resources": {"cpu": 20,
                                                      "memory_mb": 16}}]}]}}
        req = _ur.Request(f"http://127.0.0.1:{follower_port}/v1/jobs",
                          data=json.dumps(batch).encode(), method="PUT",
                          headers={"Content-Type": "application/json"})
        _ur.urlopen(req, timeout=15)
        deadline = time.monotonic() + 60
        done = False
        while time.monotonic() < deadline and not done:
            try:
                allocs = wait_http(
                    f"http://127.0.0.1:{follower_port}"
                    "/v1/job/raftbatch/allocations", timeout=5)
                done = bool(allocs) and all(
                    a["client_status"] == "complete" for a in allocs)
            except AssertionError:
                pass
            time.sleep(0.5)
        assert done, "batch job never completed on the raft cluster"
    finally:
        for p in procs:
            p.terminate()
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
