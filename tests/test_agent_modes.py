"""External-binary agent tests: config-file-driven server-only and
client-only agents wired into one cluster (mirror testutil/server.go's
exec-a-real-binary harness and agent.go's server/client composition)."""

import json
import os
import subprocess
import sys
import time
import urllib.error
import urllib.request

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def wait_http(url, timeout=20.0):
    deadline = time.monotonic() + timeout
    last = None
    while time.monotonic() < deadline:
        try:
            with urllib.request.urlopen(url, timeout=2.0) as resp:
                return json.loads(resp.read())
        except Exception as e:  # noqa: BLE001
            last = e
            time.sleep(0.3)
    raise AssertionError(f"{url} never became ready: {last}")


def spawn_agent(config_path, *extra):
    proc = subprocess.Popen(
        [sys.executable, "-m", "nomad_tpu.cli", "agent",
         "-config", str(config_path), *extra],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env={**os.environ, "PYTHONPATH": REPO},
    )
    return proc


@pytest.fixture
def server_client_cluster(tmp_path):
    """A server-only agent and a client-only agent from config files."""
    server_cfg = tmp_path / "server.hcl"
    server_cfg.write_text("""
        bind_addr = "127.0.0.1"
        ports { http = 14846 }
        server {
          enabled        = true
          num_schedulers = 1
        }
    """)
    client_cfg = tmp_path / "client.json"
    client_cfg.write_text(json.dumps({
        "bind_addr": "127.0.0.1",
        "ports": {"http": 14847},
        "client": {
            "enabled": True,
            "servers": ["127.0.0.1:14846"],
            "state_dir": str(tmp_path / "state"),
            "alloc_dir": str(tmp_path / "alloc"),
            "node_class": "cfg-test",
            "meta": {"origin": "configfile"},
            "options": {"driver.raw_exec.enable": "1"},
        },
    }))
    server = spawn_agent(server_cfg)
    try:
        wait_http("http://127.0.0.1:14846/v1/status/leader")
        client = spawn_agent(client_cfg)
        try:
            yield server, client
        finally:
            client.terminate()
            client.wait(timeout=10)
    finally:
        server.terminate()
        server.wait(timeout=10)


def test_server_only_and_client_only_agents(server_client_cluster, tmp_path):
    server, client = server_client_cluster
    # The client registers against the server-only agent with the
    # attributes from its config file.
    deadline = time.monotonic() + 20
    nodes = []
    while time.monotonic() < deadline:
        nodes = wait_http("http://127.0.0.1:14846/v1/nodes")
        if nodes and nodes[0].get("status") == "ready":
            break
        time.sleep(0.3)
    assert nodes, "client never registered"
    assert nodes[0]["node_class"] == "cfg-test"

    node = wait_http(f"http://127.0.0.1:14846/v1/node/{nodes[0]['id']}")
    assert node["meta"]["origin"] == "configfile"

    # A job submitted to the server runs on the client-only agent.
    jobfile = tmp_path / "job.hcl"
    jobfile.write_text("""
        job "cfgjob" {
          datacenters = ["dc1"]
          type = "batch"
          group "g" {
            restart { attempts = 0  mode = "fail" }
            task "t" {
              driver = "raw_exec"
              config { command = "/bin/sh"  args = ["-c", "exit 0"] }
              resources { cpu = 50  memory = 32 }
            }
          }
        }
    """)
    out = subprocess.run(
        [sys.executable, "-m", "nomad_tpu.cli",
         "--address", "http://127.0.0.1:14846", "run", "-detach",
         str(jobfile)],
        capture_output=True, text=True,
        env={**os.environ, "PYTHONPATH": REPO}, timeout=60)
    assert out.returncode == 0, out.stdout + out.stderr

    deadline = time.monotonic() + 30
    final = None
    while time.monotonic() < deadline:
        allocs = wait_http(
            "http://127.0.0.1:14846/v1/job/cfgjob/allocations")
        if allocs and allocs[0]["client_status"] == "complete":
            final = allocs[0]
            break
        time.sleep(0.3)
    assert final is not None, "batch job never completed on client agent"

    # The client-only agent serves its own HTTP endpoint: fs/logs for
    # its allocations are reachable there (every agent serves HTTP,
    # agent.go), while server-backed routes answer 501.
    listing = wait_http(
        f"http://127.0.0.1:14847/v1/client/fs/ls/{final['id']}")
    assert any(e["name"] == "alloc" for e in listing)
    servers = wait_http("http://127.0.0.1:14847/v1/agent/servers")
    assert servers == ["http://127.0.0.1:14846"]
    try:
        urllib.request.urlopen("http://127.0.0.1:14847/v1/jobs", timeout=5)
        raise AssertionError("server route should 501 on client-only agent")
    except urllib.error.HTTPError as e:
        assert e.code == 501


def test_agent_requires_role(tmp_path):
    """An agent with neither server nor client enabled refuses to start."""
    cfg = tmp_path / "empty.hcl"
    cfg.write_text('region = "eu"\n')
    proc = spawn_agent(cfg)
    out, _ = proc.communicate(timeout=30)
    assert proc.returncode == 1
    assert "must have server, client, or both" in out


def test_agent_bad_config_errors(tmp_path):
    cfg = tmp_path / "bad.hcl"
    cfg.write_text('nonsense_key = true\n')
    proc = spawn_agent(cfg)
    out, _ = proc.communicate(timeout=30)
    assert proc.returncode == 1
    assert "unknown config keys" in out
