"""State store tests (mirror nomad/state/state_store_test.go scenarios)."""

import threading

from nomad_tpu import mock
from nomad_tpu.state import StateStore, watch
from nomad_tpu.structs import consts


def test_upsert_node_and_indexes():
    s = StateStore()
    n = mock.node()
    s.upsert_node(1000, n)
    out = s.node_by_id(n.id)
    assert out.id == n.id
    assert out.create_index == 1000 and out.modify_index == 1000
    assert s.index("nodes") == 1000
    assert s.latest_index() == 1000


def test_update_node_status():
    s = StateStore()
    n = mock.node()
    s.upsert_node(1, n)
    s.update_node_status(2, n.id, consts.NODE_STATUS_DOWN)
    assert s.node_by_id(n.id).status == consts.NODE_STATUS_DOWN
    assert s.node_by_id(n.id).modify_index == 2


def test_update_node_drain():
    s = StateStore()
    n = mock.node()
    s.upsert_node(1, n)
    s.update_node_drain(2, n.id, True)
    assert s.node_by_id(n.id).drain is True


def test_snapshot_isolation():
    s = StateStore()
    n = mock.node()
    s.upsert_node(1, n)
    snap = s.snapshot()
    n2 = mock.node()
    s.upsert_node(2, n2)
    assert len(snap.nodes()) == 1
    assert len(s.snapshot().nodes()) == 2
    assert snap.latest_index() == 1


def test_index_set_isolation_under_hot_key():
    """The secondary indexes mutate a set in place only while it is
    private (created/copied since the last snapshot) — a snapshot's
    view of a hot key must not grow or shrink under later writes."""
    s = StateStore()

    def mk():
        a = mock.alloc()
        a.job_id = "hot"
        return [a]

    s.upsert_allocs(1, mk())
    snap1 = s.snapshot()
    # These adds hit the in-place path (sets copied once post-share,
    # then mutated privately): snap1 must keep seeing exactly 1.
    for i in range(2, 6):
        s.upsert_allocs(i, mk())
    assert len(snap1.allocs_by_job("hot")) == 1
    assert len(s.snapshot().allocs_by_job("hot")) == 5
    # Same for removal: deleting from the live index leaves snapshots
    # intact, including one taken mid-burst.
    snap5 = s.snapshot()
    doomed = [a.id for a in s.snapshot().allocs_by_job("hot")][:3]
    s.delete_evals(6, [], doomed)
    assert len(snap5.allocs_by_job("hot")) == 5
    assert len(snap1.allocs_by_job("hot")) == 1
    assert len(s.snapshot().allocs_by_job("hot")) == 2


def test_upsert_job_preserves_create_index():
    s = StateStore()
    j = mock.job()
    s.upsert_job(10, j)
    j2 = j.copy()
    j2.priority = 70
    s.upsert_job(20, j2)
    out = s.job_by_id(j.id)
    assert out.create_index == 10
    assert out.modify_index == 20
    assert out.job_modify_index == 20
    assert out.priority == 70


def test_job_summary_created():
    s = StateStore()
    j = mock.job()
    s.upsert_job(10, j)
    summary = s.job_summary_by_id(j.id)
    assert summary is not None
    assert "web" in summary.summary


def test_upsert_allocs_and_queries():
    s = StateStore()
    j = mock.job()
    s.upsert_job(5, j)
    a = mock.alloc()
    a.job = j
    a.job_id = j.id
    s.upsert_allocs(10, [a])
    assert s.alloc_by_id(a.id).id == a.id
    assert [x.id for x in s.allocs_by_job(j.id)] == [a.id]
    assert [x.id for x in s.allocs_by_node(a.node_id)] == [a.id]
    assert [x.id for x in s.allocs_by_eval(a.eval_id)] == [a.id]
    # job derived status: alloc is non-terminal -> running
    assert s.job_by_id(j.id).status == consts.JOB_STATUS_RUNNING


def test_upsert_allocs_copies_shared_metrics():
    """The TPU pinned-placement path shares ONE AllocMetric across a
    plan's successful allocs (scheduler/tpu.py); the store's upsert
    copy must deep-copy it per stored alloc so no later in-place
    mutation of one alloc's metrics can alter its siblings."""
    from nomad_tpu.structs.alloc import AllocMetric

    s = StateStore()
    shared = AllocMetric()
    shared.evaluate_node()
    a1, a2 = mock.alloc(), mock.alloc()
    a1.metrics = a2.metrics = shared
    s.upsert_allocs(10, [a1, a2])
    m1 = s.alloc_by_id(a1.id).metrics
    m2 = s.alloc_by_id(a2.id).metrics
    assert m1 is not shared and m2 is not shared and m1 is not m2
    m1.nodes_evaluated = 999
    assert m2.nodes_evaluated != 999


def test_upsert_allocs_preserves_client_status():
    s = StateStore()
    a = mock.alloc()
    s.upsert_allocs(10, [a])
    cl = a.copy()
    cl.client_status = consts.ALLOC_CLIENT_RUNNING
    s.update_allocs_from_client(11, [cl])
    # scheduler-side re-upsert must not clobber the client status
    sched = a.copy()
    sched.desired_status = consts.ALLOC_DESIRED_RUN
    s.upsert_allocs(12, [sched])
    out = s.alloc_by_id(a.id)
    assert out.client_status == consts.ALLOC_CLIENT_RUNNING
    assert out.modify_index == 12


def test_update_allocs_from_client_keeps_alloc_modify_index():
    s = StateStore()
    a = mock.alloc()
    s.upsert_allocs(10, [a])
    cl = a.copy()
    cl.client_status = consts.ALLOC_CLIENT_RUNNING
    s.update_allocs_from_client(11, [cl])
    out = s.alloc_by_id(a.id)
    assert out.alloc_modify_index == 10  # client writes don't bump it
    assert out.modify_index == 11


def test_allocs_by_node_terminal():
    s = StateStore()
    a1 = mock.alloc()
    a2 = mock.alloc()
    a2.node_id = a1.node_id
    a2.desired_status = consts.ALLOC_DESIRED_STOP
    s.upsert_allocs(10, [a1, a2])
    live = s.allocs_by_node_terminal(a1.node_id, False)
    term = s.allocs_by_node_terminal(a1.node_id, True)
    assert [a.id for a in live] == [a1.id]
    assert [a.id for a in term] == [a2.id]


def test_upsert_evals_and_job_summary_queued():
    s = StateStore()
    j = mock.job()
    s.upsert_job(5, j)
    e = mock.eval()
    e.job_id = j.id
    e.queued_allocations = {"web": 4}
    s.upsert_evals(10, [e])
    assert s.eval_by_id(e.id).modify_index == 10
    assert [x.id for x in s.evals_by_job(j.id)] == [e.id]
    assert s.job_summary_by_id(j.id).summary["web"].queued == 4
    # eval pending + no allocs -> job pending
    assert s.job_by_id(j.id).status == consts.JOB_STATUS_PENDING


def test_delete_evals_and_allocs():
    s = StateStore()
    e = mock.eval()
    a = mock.alloc()
    s.upsert_evals(10, [e])
    s.upsert_allocs(11, [a])
    s.delete_evals(12, [e.id], [a.id])
    assert s.eval_by_id(e.id) is None
    assert s.alloc_by_id(a.id) is None
    assert s.allocs_by_job(a.job_id) == []


def test_fresh_job_status_pending():
    """A new job with nothing outstanding is pending; dead only applies
    once terminal evals/allocs exist (state_store.go:1457)."""
    s = StateStore()
    j = mock.job()
    s.upsert_job(5, j)
    assert s.job_by_id(j.id).status == consts.JOB_STATUS_PENDING
    from nomad_tpu.structs import PeriodicConfig

    jp = mock.job()
    jp.periodic = PeriodicConfig(enabled=True, spec="0 0 * * *")
    s.upsert_job(6, jp)
    assert s.job_by_id(jp.id).status == consts.JOB_STATUS_RUNNING


def test_job_status_dead_after_eval_gc():
    s = StateStore()
    j = mock.job()
    s.upsert_job(5, j)
    e = mock.eval()
    e.job_id = j.id
    s.upsert_evals(6, [e])
    s.delete_evals(7, [e.id], [])
    assert s.job_by_id(j.id).status == consts.JOB_STATUS_DEAD


def test_job_status_dead_after_terminal():
    s = StateStore()
    j = mock.job()
    s.upsert_job(5, j)
    e = mock.eval()
    e.job_id = j.id
    s.upsert_evals(6, [e])
    assert s.job_by_id(j.id).status == consts.JOB_STATUS_PENDING
    e2 = e.copy()
    e2.status = consts.EVAL_STATUS_COMPLETE
    s.upsert_evals(7, [e2])
    assert s.job_by_id(j.id).status == consts.JOB_STATUS_DEAD


def test_watch_fires_on_write():
    s = StateStore()
    ev = s.watch([watch.table("nodes")])
    assert not ev.is_set()
    s.upsert_node(1, mock.node())
    assert ev.wait(1.0)


def test_watch_scoped_to_job():
    s = StateStore()
    j1, j2 = mock.job(), mock.job()
    s.upsert_job(1, j1)
    s.upsert_job(2, j2)
    a1 = mock.alloc()
    a1.job_id = j1.id
    ev = s.watch([watch.alloc_job(j2.id)])
    s.upsert_allocs(3, [a1])
    assert not ev.is_set()
    a2 = mock.alloc()
    a2.job_id = j2.id
    s.upsert_allocs(4, [a2])
    assert ev.wait(1.0)


def test_persist_restore_roundtrip():
    s = StateStore()
    j = mock.job()
    n = mock.node()
    e = mock.eval()
    a = mock.alloc()
    a.job_id = j.id
    s.upsert_job(1, j)
    s.upsert_node(2, n)
    s.upsert_evals(3, [e])
    s.upsert_allocs(4, [a])
    data = s.persist()
    s2 = StateStore.restore(data)
    assert s2.latest_index() == 4
    assert s2.job_by_id(j.id) is not None
    assert s2.node_by_id(n.id) is not None
    assert s2.eval_by_id(e.id) is not None
    assert [x.id for x in s2.allocs_by_job(j.id)] == [a.id]


def test_concurrent_snapshot_consistency():
    """Writers must never corrupt a reader's snapshot."""
    s = StateStore()
    stop = threading.Event()
    errors = []

    def writer():
        i = 1
        while not stop.is_set():
            s.upsert_node(i, mock.node())
            i += 1

    def reader():
        while not stop.is_set():
            snap = s.snapshot()
            nodes = snap.nodes()
            if len(nodes) != len(snap.nodes()):
                errors.append("snapshot changed size")

    threads = [threading.Thread(target=writer), threading.Thread(target=reader)]
    for t in threads:
        t.start()
    import time

    time.sleep(0.3)
    stop.set()
    for t in threads:
        t.join()
    assert errors == []


def test_persist_restore_every_table_via_json():
    """Full per-table round-trip THROUGH JSON — exactly what the raft
    snapshot files store (fsm_test.go round-trips per SnapshotType)."""
    import json as _json

    from nomad_tpu.structs.alloc import VaultAccessor
    from nomad_tpu.state.store import PeriodicLaunch

    s = StateStore()
    j = mock.job()
    n = mock.node()
    e = mock.eval()
    a = mock.alloc()
    a.job_id = j.id
    a.node_id = n.id
    a.client_status = "running"
    s.upsert_job(1, j)
    s.upsert_node(2, n)
    s.upsert_evals(3, [e])
    s.upsert_allocs(4, [a])
    s.upsert_periodic_launch(5, PeriodicLaunch(id=j.id, launch=123.0))
    s.upsert_vault_accessors(6, [VaultAccessor(
        accessor="acc1", alloc_id=a.id, task="web", node_id=n.id,
        policies=["p1"])])

    data = _json.loads(_json.dumps(s.persist()))  # the raft wire format
    s2 = StateStore.restore(data)

    assert s2.latest_index() == 6
    assert s2.job_by_id(j.id).name == j.name
    assert s2.node_by_id(n.id).datacenter == n.datacenter
    assert s2.eval_by_id(e.id).priority == e.priority
    # secondary indexes rebuilt, not just primary rows
    assert [x.id for x in s2.allocs_by_job(j.id)] == [a.id]
    assert [x.id for x in s2.allocs_by_node(n.id)] == [a.id]
    assert [x.id for x in s2.allocs_by_eval(a.eval_id)] == [a.id]
    launch = s2.periodic_launch_by_id(j.id)
    assert launch is not None and launch.launch == 123.0
    accs = s2.vault_accessors_by_alloc(a.id)
    assert [v.accessor for v in accs] == ["acc1"]
    # derived job summary survives
    summary = s2.job_summary_by_id(j.id)
    assert summary is not None
    assert summary.summary["web"].running == 1
    # client-side fields preserved
    assert s2.alloc_by_id(a.id).client_status == "running"
