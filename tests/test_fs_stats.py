"""Alloc filesystem endpoints, log reading, and client stats (reference
command/agent/fs_endpoint.go, client/allocdir file APIs, stats/host.go)."""

import os
import time

import pytest

from nomad_tpu import mock
from nomad_tpu.api import HTTPServer
from nomad_tpu.api.client import APIError, Client
from nomad_tpu.client import ClientAgent, ClientConfig
from nomad_tpu.server import Server, ServerConfig
from nomad_tpu.structs import consts


def wait_until(fn, timeout=8.0, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if fn():
            return True
        time.sleep(interval)
    return False


@pytest.fixture
def cluster(tmp_path):
    server = Server(ServerConfig(num_schedulers=1, eval_nack_timeout=5.0))
    server.start()
    http = HTTPServer(server)
    http.start()
    cfg = ClientConfig(
        servers=[http.addr],
        state_dir=str(tmp_path / "state"),
        alloc_dir=str(tmp_path / "allocs"),
        options={"driver.raw_exec.enable": "1"},
        dev_mode=True,
    )
    os.makedirs(cfg.state_dir, exist_ok=True)
    agent = ClientAgent(cfg)
    agent.start()
    http.client = agent
    yield server, agent, Client(http.addr, timeout=10.0)
    agent.shutdown(destroy_allocs=True)
    http.stop()
    server.shutdown()


def _run_echo_job(server, text="hello fs", run_for=30):
    job = mock.job()
    tg = job.task_groups[0]
    tg.count = 1
    task = tg.tasks[0]
    task.driver = "raw_exec"
    task.config = {
        "command": "/bin/sh",
        "args": ["-c", f"echo '{text}'; sleep {run_for}"],
    }
    task.resources.networks = []
    server.job_register(job)
    assert wait_until(
        lambda: any(
            a.client_status == consts.ALLOC_CLIENT_RUNNING
            for a in server.fsm.state.allocs_by_job(job.id)
        )
    )
    return server.fsm.state.allocs_by_job(job.id)[0]


def test_fs_list_stat_cat(cluster):
    server, agent, api = cluster
    alloc = _run_echo_job(server)

    # alloc root has the shared dir plus one dir per task
    names = {e["name"] for e in api.alloc_fs.list(alloc.id, "/")}
    assert "alloc" in names and "web" in names

    st = api.alloc_fs.stat(alloc.id, "alloc/logs")
    assert st["is_dir"]

    # stdout log is under alloc/logs/<task>.stdout.0
    assert wait_until(
        lambda: any(
            e["name"] == "web.stdout.0" and e["size"] > 0
            for e in api.alloc_fs.list(alloc.id, "alloc/logs")
        )
    )
    data = api.alloc_fs.cat(alloc.id, "alloc/logs/web.stdout.0")
    assert b"hello fs" in data

    # read_at with offset/limit
    part = api.alloc_fs.read_at(alloc.id, "alloc/logs/web.stdout.0", offset=6, limit=2)
    assert part == b"fs"


def test_fs_path_escape_rejected(cluster):
    server, agent, api = cluster
    alloc = _run_echo_job(server)
    with pytest.raises(APIError) as e:
        api.alloc_fs.list(alloc.id, "../../")
    assert e.value.status == 403


def test_fs_unknown_alloc_404s_or_errors(cluster):
    server, agent, api = cluster
    with pytest.raises(APIError):
        api.alloc_fs.list("no-such-alloc", "/")


def test_logs_endpoint_and_follow_offsets(cluster):
    server, agent, api = cluster
    alloc = _run_echo_job(server, text="line one")

    assert wait_until(
        lambda: api.alloc_fs.logs(alloc.id, "web")["data"] != b""
    )
    out = api.alloc_fs.logs(alloc.id, "web")
    assert b"line one" in out["data"]
    offset = out["offset"]

    # no new output -> empty poll at the returned offset
    again = api.alloc_fs.logs(alloc.id, "web", offset=offset)
    assert again["data"] == b""

    # tail-from-end origin
    tail = api.alloc_fs.logs(alloc.id, "web", offset=4, origin="end")
    assert tail["data"] == b"one\n"


def test_client_host_stats(cluster):
    server, agent, api = cluster
    from nomad_tpu.api.client import ClientStats

    stats = ClientStats(api)
    host = stats.host()
    assert host["memory"]["total"] > 0
    assert host["uptime"] > 0
    assert isinstance(host["load_avg"], list) and len(host["load_avg"]) == 3


def test_alloc_stats_samples_real_pid(cluster):
    server, agent, api = cluster
    alloc = _run_echo_job(server)
    from nomad_tpu.api.client import ClientStats

    stats = ClientStats(api)
    out = stats.allocation(alloc.id)
    usage = out["tasks"]["web"]
    assert usage is not None and usage["pid"] > 0
    assert usage["rss_bytes"] > 0


def test_mock_driver_task_has_no_pid_stats(cluster):
    server, agent, api = cluster
    job = mock.job()
    tg = job.task_groups[0]
    tg.count = 1
    task = tg.tasks[0]
    task.driver = "mock_driver"
    task.config = {"run_for": 1e9}
    task.resources.networks = []
    server.job_register(job)
    assert wait_until(
        lambda: any(
            a.client_status == consts.ALLOC_CLIENT_RUNNING
            for a in server.fsm.state.allocs_by_job(job.id)
        )
    )
    alloc = server.fsm.state.allocs_by_job(job.id)[0]
    out = agent.alloc_stats(alloc.id)
    assert out["tasks"]["web"] is None
