"""Artifact fetching + template rendering (reference client/getter,
client/consul_template.go) and their task-prestart integration."""

import hashlib
import http.server
import os
import tarfile
import threading
import time
import zipfile

import pytest

from nomad_tpu.client.allocdir import AllocDir
from nomad_tpu.client.getter import ArtifactError, fetch_artifact
from nomad_tpu.client.task_runner import TaskRunner
from nomad_tpu.client.template import TaskTemplateManager, render_template
from nomad_tpu import mock
from nomad_tpu.structs import TaskArtifact, Template, consts


# ---------------------------------------------------------------- getter


def test_fetch_local_file(tmp_path):
    src = tmp_path / "payload.bin"
    src.write_bytes(b"data123")
    task_dir = tmp_path / "task"
    task_dir.mkdir()
    art = TaskArtifact(getter_source=str(src))
    fetch_artifact(art, str(task_dir))
    out = task_dir / "payload.bin"
    assert out.read_bytes() == b"data123"
    assert os.access(out, os.X_OK)  # downloaded artifacts made executable


def test_fetch_with_relative_dest_and_checksum(tmp_path):
    src = tmp_path / "a.txt"
    src.write_bytes(b"hello")
    digest = hashlib.sha256(b"hello").hexdigest()
    task_dir = tmp_path / "task"
    task_dir.mkdir()
    art = TaskArtifact(
        getter_source=f"file://{src}",
        getter_options={"checksum": f"sha256:{digest}"},
        relative_dest="sub/dir",
    )
    fetch_artifact(art, str(task_dir))
    assert (task_dir / "sub" / "dir" / "a.txt").read_bytes() == b"hello"


def test_fetch_checksum_mismatch(tmp_path):
    src = tmp_path / "a.txt"
    src.write_bytes(b"hello")
    task_dir = tmp_path / "task"
    task_dir.mkdir()
    art = TaskArtifact(
        getter_source=str(src),
        getter_options={"checksum": "sha256:" + "0" * 64},
    )
    with pytest.raises(ArtifactError, match="checksum mismatch"):
        fetch_artifact(art, str(task_dir))
    assert not (task_dir / "a.txt").exists()


def test_fetch_http(tmp_path):
    serve_dir = tmp_path / "www"
    serve_dir.mkdir()
    (serve_dir / "remote.txt").write_bytes(b"from-http")

    class Handler(http.server.SimpleHTTPRequestHandler):
        def __init__(self, *a, **kw):
            super().__init__(*a, directory=str(serve_dir), **kw)

        def log_message(self, *a):
            pass

    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        task_dir = tmp_path / "task"
        task_dir.mkdir()
        url = f"http://127.0.0.1:{srv.server_port}/remote.txt"
        fetch_artifact(TaskArtifact(getter_source=url), str(task_dir))
        assert (task_dir / "remote.txt").read_bytes() == b"from-http"
    finally:
        srv.shutdown()


def test_fetch_unpacks_tarball(tmp_path):
    payload = tmp_path / "inner.txt"
    payload.write_text("inside")
    tar_path = tmp_path / "bundle.tar.gz"
    with tarfile.open(tar_path, "w:gz") as tf:
        tf.add(payload, arcname="inner.txt")
    task_dir = tmp_path / "task"
    task_dir.mkdir()
    fetch_artifact(TaskArtifact(getter_source=str(tar_path)), str(task_dir))
    assert (task_dir / "inner.txt").read_text() == "inside"
    assert not (task_dir / "bundle.tar.gz").exists()


def test_fetch_archive_false_keeps_archive(tmp_path):
    tar_path = tmp_path / "bundle.tar"
    with tarfile.open(tar_path, "w") as tf:
        pass
    task_dir = tmp_path / "task"
    task_dir.mkdir()
    fetch_artifact(
        TaskArtifact(getter_source=str(tar_path),
                     getter_options={"archive": "false"}),
        str(task_dir),
    )
    assert (task_dir / "bundle.tar").exists()


def test_fetch_zip_escape_rejected(tmp_path):
    zip_path = tmp_path / "evil.zip"
    with zipfile.ZipFile(zip_path, "w") as zf:
        zf.writestr("../escape.txt", "boom")
    task_dir = tmp_path / "task"
    task_dir.mkdir()
    with pytest.raises(ArtifactError, match="escapes dest"):
        fetch_artifact(TaskArtifact(getter_source=str(zip_path)), str(task_dir))


def test_dest_escape_rejected(tmp_path):
    task_dir = tmp_path / "task"
    task_dir.mkdir()
    art = TaskArtifact(getter_source="/etc/hostname", relative_dest="../../out")
    with pytest.raises(ArtifactError, match="escapes task dir"):
        fetch_artifact(art, str(task_dir))


# -------------------------------------------------------------- template


def test_render_template_functions(tmp_path):
    (tmp_path / "inc.txt").write_text("included")
    out = render_template(
        'port={{ env "PORT" }} svc={{ key "svc/web" }} body={{ file "inc.txt" }}',
        env={"PORT": "8080"},
        kv=lambda p: {"svc/web": "10.0.0.1"}.get(p),
        task_dir=str(tmp_path),
    )
    assert out == "port=8080 svc=10.0.0.1 body=included"


def test_render_missing_values_empty():
    out = render_template('a={{ env "NOPE" }} b={{ key "nope" }}',
                          env={}, kv=lambda p: None)
    assert out == "a= b="


def test_template_manager_renders_and_watches_change(tmp_path):
    task = mock.job().task_groups[0].tasks[0]
    task.templates = [
        Template(embedded_tmpl='value={{ key "cfg" }}',
                 dest_path="local/app.conf", change_mode="restart", splay=0.0),
    ]
    kv_store = {"cfg": "one"}
    changes = []
    mgr = TaskTemplateManager(
        task, env={}, task_dir=str(tmp_path), kv=kv_store.get,
        on_change=lambda mode, sig: changes.append((mode, sig)),
    )
    mgr.POLL_INTERVAL = 0.1
    mgr.render_all()
    dest = tmp_path / "local" / "app.conf"
    assert dest.read_text() == "value=one"

    mgr.start()
    try:
        kv_store["cfg"] = "two"
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and not changes:
            time.sleep(0.05)
        assert changes == [("restart", "")]
        assert dest.read_text() == "value=two"
    finally:
        mgr.stop()


def test_template_signal_mode_precedence(tmp_path):
    task = mock.job().task_groups[0].tasks[0]
    task.templates = [
        Template(embedded_tmpl='{{ key "a" }}', dest_path="a",
                 change_mode="signal", change_signal="SIGHUP", splay=0.0),
        Template(embedded_tmpl='{{ key "b" }}', dest_path="b",
                 change_mode="restart", splay=0.0),
    ]
    kv_store = {"a": "1", "b": "1"}
    changes = []
    mgr = TaskTemplateManager(
        task, env={}, task_dir=str(tmp_path), kv=kv_store.get,
        on_change=lambda mode, sig: changes.append((mode, sig)),
    )
    mgr.POLL_INTERVAL = 0.1
    mgr.render_all()
    mgr.start()
    try:
        kv_store["a"] = "2"
        kv_store["b"] = "2"
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and not changes:
            time.sleep(0.05)
        # restart dominates signal when both changed in one round
        assert changes[0][0] == "restart"
    finally:
        mgr.stop()


# ------------------------------------------------- task runner prestart


def make_runner(tmp_path, task, states):
    alloc = mock.alloc()
    alloc.job.task_groups[0].tasks = [task]
    alloc.task_group = alloc.job.task_groups[0].name
    adir = AllocDir(str(tmp_path / "alloc"))

    def cb(name, st):
        states.append((st.state, [e.type for e in st.events]))

    return TaskRunner(alloc, task, adir, cb)


def test_prestart_artifact_and_template_e2e(tmp_path):
    src = tmp_path / "greeting.txt"
    src.write_text("salutations")
    task = mock.job().task_groups[0].tasks[0]
    task.name = "web"
    task.driver = "raw_exec"
    task.artifacts = [TaskArtifact(getter_source=str(src))]
    task.templates = [
        Template(embedded_tmpl='greet={{ env "GREETING" }}',
                 dest_path="local/app.conf", change_mode="noop"),
    ]
    task.env = {"GREETING": "bonjour"}
    task.config = {
        "command": "/bin/sh",
        "args": ["-c", "cat greeting.txt local/app.conf"],
    }
    tg = mock.job().task_groups[0]

    states = []
    runner = make_runner(tmp_path, task, states)
    runner.alloc.job.task_groups[0].restart_policy.attempts = 0
    runner.alloc.job.task_groups[0].restart_policy.mode = "fail"
    runner.alloc_dir.build([task.name])
    runner.run()

    assert runner.state.state == consts.TASK_STATE_DEAD
    assert not runner.state.failed
    types = [e.type for e in runner.state.events]
    assert consts.TASK_EVENT_DOWNLOADING_ARTIFACTS in types
    logs = runner.alloc_dir.log_dir()
    out = b""
    for _ in range(50):
        try:
            out = open(os.path.join(logs, "web.stdout.0"), "rb").read()
        except OSError:
            out = b""
        if b"salutations" in out:
            break
        time.sleep(0.1)
    assert b"salutations" in out
    assert b"greet=bonjour" in out


def test_prestart_artifact_failure_respects_restart_policy(tmp_path):
    task = mock.job().task_groups[0].tasks[0]
    task.name = "web"
    task.driver = "mock_driver"
    task.config = {"run_for": 0.1}
    task.artifacts = [TaskArtifact(getter_source="/no/such/file-xyz")]

    states = []
    runner = make_runner(tmp_path, task, states)
    runner.alloc.job.task_groups[0].restart_policy.attempts = 0
    runner.alloc.job.task_groups[0].restart_policy.mode = "fail"
    runner.restart_tracker.policy.attempts = 0
    runner.restart_tracker.policy.mode = "fail"
    runner.alloc_dir.build([task.name])
    runner.run()

    assert runner.state.state == consts.TASK_STATE_DEAD
    assert runner.state.failed
    types = [e.type for e in runner.state.events]
    assert consts.TASK_EVENT_ARTIFACT_DOWNLOAD_FAILED in types


def test_template_restart_cycles_task_without_policy(tmp_path):
    """change_mode=restart re-runs the task without consuming restart
    attempts (consul_template.go deliberate restarts)."""
    task = mock.job().task_groups[0].tasks[0]
    task.name = "web"
    task.driver = "raw_exec"
    task.config = {"command": "/bin/sh", "args": ["-c", "sleep 600"]}
    kv_store = {"cfg": "one"}
    task.templates = [
        Template(embedded_tmpl='v={{ key "cfg" }}', dest_path="local/c",
                 change_mode="restart", splay=0.0),
    ]

    states = []
    runner = make_runner(tmp_path, task, states)
    runner.template_kv = kv_store.get
    runner.restart_tracker.policy.attempts = 0
    runner.restart_tracker.policy.mode = "fail"
    runner.alloc_dir.build([task.name])
    runner.start()
    try:
        deadline = time.monotonic() + 20.0
        while time.monotonic() < deadline and runner.handle is None:
            time.sleep(0.05)
        assert runner.handle is not None
        pid1 = runner.handle.pid()
        runner._template_manager.POLL_INTERVAL = 0.1

        kv_store["cfg"] = "two"
        deadline = time.monotonic() + 20.0
        while time.monotonic() < deadline:
            h = runner.handle
            if h is not None and h.pid() and h.pid() != pid1:
                break
            time.sleep(0.05)
        assert runner.handle.pid() != pid1  # restarted with a fresh process
        types = [e.type for e in runner.state.events]
        assert consts.TASK_EVENT_RESTART_SIGNAL in types
        assert runner.state.state == consts.TASK_STATE_RUNNING
    finally:
        runner.kill()
        runner.join(timeout=15.0)
